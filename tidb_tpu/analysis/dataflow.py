"""dataflow — interprocedural passes over an AST-derived project call
graph (ISSUE 9; ref: golang.org/x/tools/go/analysis facts + the
Engler-style "bugs as deviant behavior" inference the reference leans on
via nogo). The PR-7 passes were lexical — one file at a time — but the
bug classes that actually cost PRs are FLOW properties: a snapshot read
that bypasses `start_ts` three calls below the dispatch loop, a retry
loop whose budget consult lives in a helper, a typed error that crosses
the session boundary unmapped. These need reachability and propagation,
not grep.

Three layers:

  * **CallGraph** — module-qualified resolution of intra-package calls
    (plain functions, methods, nested closures handed to thread pools),
    with lightweight receiver typing from parameter annotations,
    `self.x = Class(...)` constructor assignments and dataclass field
    annotations; an unresolvable receiver falls back to unique-name
    method resolution (exactly one project class defines the method).
  * **TaintAnalysis** — a small forward fact-propagation framework:
    facts seed at the request-path roots and flow through assignments,
    containers (coarse), call arguments and returns to a fixpoint.
  * the three passes:
      dataflow-snapshot      every MVCC read reachable from the request
                             path must flow a `start_ts` (latest-version
                             `kv.get`/`kv.scan` there is a finding)
      dataflow-backoff       request-path retry loops must consult a
                             Backoffer budget; request-path sleeps must
                             be the Backoffer's sliced, deadline-clamped
                             one — never a raw `time.sleep`
      dataflow-error-escape  interprocedural raise/catch reachability:
                             bare RuntimeError/Exception must not escape
                             a request root, and typed request-path
                             errors must be mapped to a SQLError code
                             before crossing the session boundary
                             (supersedes PR-7's lexical error-taxonomy)

Roots are the live request-path entry points (distsql select /
select_stream, the TPUStore coprocessor endpoints, TxnEngine.commit);
fixtures declare their own with `# vet: request-path-root` on the def
line and `# vet: session-boundary` for the boundary function.
"""

from __future__ import annotations

import ast
import builtins
import os
import re
from dataclasses import dataclass, field

from .common import Finding, SourceFile

PASS_SNAPSHOT = "dataflow-snapshot"
PASS_BACKOFF = "dataflow-backoff"
PASS_ESCAPE = "dataflow-error-escape"

_ROOT_MARK = re.compile(r"#\s*vet:\s*request-path-root")
_BOUNDARY_MARK = re.compile(r"#\s*vet:\s*session-boundary")

# live-tree request-path roots: (rel-suffix, class-or-None, func name).
# These are the MVCC-read / retry-loop paths the snapshot and backoff
# passes police.
REQUEST_ROOTS = (
    ("distsql/dispatch.py", None, "select"),
    ("distsql/dispatch.py", None, "select_stream"),
    ("store/store.py", "TPUStore", "coprocessor"),
    ("store/store.py", "TPUStore", "batch_coprocessor"),
    ("store/store.py", "TPUStore", "coprocessor_bytes"),
    ("store/store.py", "TPUStore", "batch_coprocessor_bytes"),
)
# extra roots for the escape pass only: the write path's typed errors
# (TxnError) must map at the boundary too — but its LEGITIMATE
# latest-version reads (write-conflict checks) are not snapshot reads,
# so the snapshot pass must not police them
ESCAPE_EXTRA_ROOTS = (
    ("store/txn.py", "TxnEngine", "commit"),
)
# CDC entry points (ISSUE 10 satellite): the SQL changefeed statements,
# the /cdc/api/v1 handlers and the sink flush loop are request-path
# roots for the ESCAPE and BACKOFF passes — typed CDC errors must map at
# the boundary and the flush/recovery loops must never spin or raw-sleep.
# NOT snapshot roots: the incremental scans read version RANGES
# (scan_versions), not statement snapshots.
CDC_ROOTS = (
    ("sql/session.py", "Session", "_changefeed"),
    ("server/http_api.py", "StatusServer", "_cdc_route"),
    ("cdc/hub.py", "ChangefeedHub", "tick"),
)
# columnar replica entry points (ISSUE 12 satellite): the engine-routed
# read path, the compaction tick, the apply sink, and the HTTP view are
# ESCAPE and BACKOFF roots — typed staleness must never spin or
# raw-sleep (the data_not_ready wait rides a Backoffer budget) and no
# bare error may escape. NOT snapshot roots: the replica reads typed
# delta/stable layers, never MVCC kv at a latest-version ts.
COLUMNAR_ROOTS = (
    ("columnar/route.py", None, "try_columnar_select"),
    ("columnar/replica.py", "ColumnarReplica", "compact_tick"),
    ("columnar/sink.py", "ColumnarSink", "write"),
    ("server/http_api.py", "StatusServer", "_columnar_route"),
)
# production front door (ISSUE 15): the admission gate's two entry
# points are ESCAPE and BACKOFF roots — a shed must leave as the typed
# AdmissionShed (mapped to MySQL 9003 at the session boundary) and the
# gate's bounded queue wait must never spin or raw-sleep. The plan-cache
# consult/serve seam is an ESCAPE-only root (below): its cone reaches
# the planner/parser, whose scanning loops are not retry loops — but no
# bare error may escape a cache hit any more than a cold plan. NOT
# snapshot roots: the cache serves templates, never MVCC reads (those
# happen below dispatch, already policed).
FRONT_DOOR_ROOTS = (
    ("server/admission.py", "AdmissionGate", "admit"),
    ("server/admission.py", "AdmissionGate", "before_dispatch"),
)
FRONT_DOOR_ESCAPE_ROOTS = (
    ("sql/session.py", "Session", "_plan_cache_begin"),
)
# Top SQL (ISSUE 17): the HTTP reporter view and the PD-tick rotation
# are ESCAPE and BACKOFF roots — reporter reads must leave typed (a
# broken window serialization may not 500 as a bare KeyError) and the
# collector's seal path must never spin or raw-sleep under its leaf
# lock. NOT snapshot roots: the collector reads its own ring, never
# MVCC kv.
TOPSQL_ROOTS = (
    ("server/http_api.py", "StatusServer", "_topsql_route"),
    ("topsql/reporter.py", "TopSQLCollector", "rotate"),
)
# MPP dispatch (ISSUE 18): the fragment coordinator is an ESCAPE and
# BACKOFF root — every decline must be a counted fallback or a typed
# region/staleness error at the boundary (never a bare escape from the
# wire round-trip or the replica readiness gate), and the data_not_ready
# wait it inherits from the columnar path must ride a Backoffer budget.
# NOT a snapshot root: probe scans go through distsql.select / the
# replica's typed layers, both already policed.
MPP_ROOTS = (
    ("mpp/dispatch.py", None, "try_mpp_select"),
)
# cross-session fused execution (ISSUE 19): the coalescer's two park
# entry points are ESCAPE and BACKOFF roots — a lane must leave with a
# result, a typed error, or a counted fall-out (never a bare escape from
# the batched flush), and the leader/follower waits must be deadline'd
# condition/event waits, never a raw sleep or an unbudgeted spin. NOT
# snapshot roots: the read flush draws ONE window ts and hands it to
# batch_coprocessor, which the snapshot pass already polices.
COALESCE_ROOTS = (
    ("server/coalesce.py", "SessionCoalescer", "point_get"),
    ("server/coalesce.py", "SessionCoalescer", "group_commit"),
)
# point-in-time recovery (ISSUE 20): the restore replay loop and the
# log-backup flush are ESCAPE and BACKOFF roots — every coverage break
# must leave as the typed LogGapError (mapped to a SQLError at the
# session boundary), a flush failure must park the feed typed (never a
# bare escape from the segment writer), and neither loop may spin or
# raw-sleep. NOT snapshot roots: replay re-ingests at SOURCE commit
# timestamps and the sink buffers raw bytes — neither draws a statement
# snapshot.
PITR_ROOTS = (
    ("br/pitr.py", None, "restore_until"),
    ("br/pitr.py", "LogBackupSink", "flush"),
    ("br/pitr.py", None, "pitr_tick"),
)
SESSION_BOUNDARIES = (("sql/session.py", "Session", "execute"),)

# directories whose exception classes form the "typed request-path error"
# family the boundary check tracks (store region/txn errors, dispatch
# errors, backoff exhaustion, replication faults)
_FAMILY_DIRS = ("distsql", "store", "replication")
_FAMILY_FILES = ("util/backoff.py", "server/admission.py")

# taint facts
REQ = "REQ"  # a request-carrying object (KVRequest/CopRequest/...)
TS = "TS"  # a start_ts snapshot timestamp

_FACT_SEED_PARAMS = {"req": {REQ}, "start_ts": {TS}}


# --------------------------------------------------------------- call graph

@dataclass
class FuncInfo:
    qname: str  # "<rel>::Class.name" / "<rel>::name" / "<rel>::f.<locals>.g"
    rel: str
    cls: str | None
    name: str
    node: ast.AST
    sf: SourceFile
    params: list[str] = field(default_factory=list)
    is_root: bool = False
    is_boundary: bool = False
    # analysis state
    callees: list = field(default_factory=list)  # [(FuncInfo, Call node)]
    callers: list = field(default_factory=list)
    facts: dict = field(default_factory=dict)  # param -> set of facts
    local_facts: dict = field(default_factory=dict)  # name -> facts (post-fixpoint)
    escapes: dict = field(default_factory=dict)  # (type, rel, line) -> True
    consults_backoff: bool = False
    return_facts: set = field(default_factory=set)


@dataclass
class ClassInfo:
    key: tuple  # (rel, name)
    node: ast.ClassDef
    rel: str
    bases: list = field(default_factory=list)  # resolved keys / builtin names
    methods: dict = field(default_factory=dict)  # name -> FuncInfo
    attr_types: dict = field(default_factory=dict)  # attr -> class key


class CallGraph:
    """Project call graph + symbol tables for one file set."""

    def __init__(self, files: list[SourceFile]):
        self.files = [sf for sf in files if sf.tree is not None]
        self.by_rel = {sf.rel: sf for sf in self.files}
        self.module_of = {self._dotted(sf.rel): sf.rel for sf in self.files}
        self.funcs: dict[str, FuncInfo] = {}
        self.classes: dict[tuple, ClassInfo] = {}
        self.mod_funcs: dict[tuple, FuncInfo] = {}  # (rel, name) -> info
        self.imports: dict[str, dict] = {}  # rel -> alias -> ("mod", dotted) | ("sym", dotted, name)
        self.method_index: dict[str, list] = {}  # method name -> [ClassInfo]
        self._collect()
        self._resolve_bases_and_attrs()
        self._build_edges()

    # -- symbol collection --------------------------------------------------
    @staticmethod
    def _dotted(rel: str) -> str:
        mod = rel[:-3].replace(os.sep, ".").replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod

    def _collect(self):
        for sf in self.files:
            self.imports[sf.rel] = self._imports_of(sf)
            for node in sf.tree.body:
                self._collect_node(sf, node, cls=None, prefix="")

    def _collect_node(self, sf, node, cls, prefix):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qname = f"{sf.rel}::{prefix}{node.name}"
            fi = FuncInfo(qname, sf.rel, cls.key[1] if cls else None,
                          node.name, node, sf,
                          params=[a.arg for a in node.args.args])
            line = sf.lines[node.lineno - 1] if node.lineno <= len(sf.lines) else ""
            fi.is_root = bool(_ROOT_MARK.search(line))
            fi.is_boundary = bool(_BOUNDARY_MARK.search(line))
            self.funcs[qname] = fi
            if cls is not None and prefix == f"{cls.key[1]}.":
                cls.methods[node.name] = fi
                self.method_index.setdefault(node.name, []).append(cls)
            elif cls is None and prefix == "":
                self.mod_funcs[(sf.rel, node.name)] = fi
            for sub in ast.walk(node):
                if sub is not node and isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    sub_q = f"{sf.rel}::{prefix}{node.name}.<locals>.{sub.name}"
                    if sub_q not in self.funcs:
                        sfi = FuncInfo(sub_q, sf.rel, fi.cls, sub.name, sub, sf,
                                       params=[a.arg for a in sub.args.args])
                        self.funcs[sub_q] = sfi
        elif isinstance(node, ast.ClassDef):
            ci = ClassInfo((sf.rel, node.name), node, sf.rel)
            self.classes[ci.key] = ci
            for sub in node.body:
                self._collect_node(sf, sub, cls=ci, prefix=f"{node.name}.")

    def _imports_of(self, sf) -> dict:
        out: dict = {}
        pkg = self._dotted(sf.rel).rsplit(".", 1)[0] if "." in self._dotted(sf.rel) else ""
        is_pkg = sf.rel.endswith("__init__.py")
        self_mod = self._dotted(sf.rel)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = ("mod", a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = self_mod if is_pkg else pkg
                    parts = base.split(".") if base else []
                    if node.level > 1:
                        parts = parts[: len(parts) - (node.level - 1)]
                    base = ".".join(parts)
                    mod = f"{base}.{node.module}" if node.module else base
                else:
                    mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    out[a.asname or a.name] = ("sym", mod, a.name)
        return out

    # -- symbol resolution --------------------------------------------------
    def resolve_symbol(self, mod: str, name: str, depth: int = 0):
        """(kind, obj) for `name` exported by dotted module `mod`:
        ("func", FuncInfo) | ("class", ClassInfo) | ("mod", dotted) | None.
        Follows re-exports through package __init__ chains."""
        if depth > 6:
            return None
        sub = self.module_of.get(f"{mod}.{name}")
        if sub:
            return ("mod", f"{mod}.{name}")
        rel = self.module_of.get(mod)
        if rel is None:
            return None
        fi = self.mod_funcs.get((rel, name))
        if fi is not None:
            return ("func", fi)
        ci = self.classes.get((rel, name))
        if ci is not None:
            return ("class", ci)
        imp = self.imports.get(rel, {}).get(name)
        if imp is None:
            return None
        if imp[0] == "mod":
            return ("mod", imp[1])
        return self.resolve_symbol(imp[1], imp[2], depth + 1)

    def resolve_alias(self, rel: str, name: str):
        """Resolve a bare name used in `rel`: local def, then imports."""
        fi = self.mod_funcs.get((rel, name))
        if fi is not None:
            return ("func", fi)
        ci = self.classes.get((rel, name))
        if ci is not None:
            return ("class", ci)
        imp = self.imports.get(rel, {}).get(name)
        if imp is None:
            return None
        if imp[0] == "mod":
            return ("mod", imp[1])
        return self.resolve_symbol(imp[1], imp[2])

    def _resolve_bases_and_attrs(self):
        for ci in self.classes.values():
            for b in ci.node.bases:
                if isinstance(b, ast.Name):
                    r = self.resolve_alias(ci.rel, b.id)
                    ci.bases.append(r[1].key if r and r[0] == "class" else b.id)
                elif isinstance(b, ast.Attribute):
                    ci.bases.append(b.attr)
            # dataclass-style field annotations
            for node in ci.node.body:
                if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                    t = self._annotation_class(ci.rel, node.annotation)
                    if t is not None:
                        ci.attr_types[node.target.id] = t.key
            # `self.x = Class(...)` / `self.x: T = ...` in method bodies
            for m in ci.methods.values():
                for node in ast.walk(m.node):
                    tgt = None
                    val = None
                    if isinstance(node, ast.Assign) and len(node.targets) == 1:
                        tgt, val = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign):
                        tgt, val = node.target, node.value
                    if not (isinstance(tgt, ast.Attribute) and
                            isinstance(tgt.value, ast.Name) and tgt.value.id == "self"):
                        continue
                    if isinstance(node, ast.AnnAssign):
                        t = self._annotation_class(ci.rel, node.annotation)
                        if t is not None:
                            ci.attr_types.setdefault(tgt.attr, t.key)
                            continue
                    if isinstance(val, ast.Call) and isinstance(val.func, ast.Name):
                        r = self.resolve_alias(ci.rel, val.func.id)
                        if r and r[0] == "class":
                            ci.attr_types.setdefault(tgt.attr, r[1].key)

    def _annotation_class(self, rel: str, ann) -> ClassInfo | None:
        name = None
        if isinstance(ann, ast.Name):
            name = ann.id
        elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip().split("|")[0].strip()
        elif isinstance(ann, ast.Attribute):
            name = ann.attr
        elif isinstance(ann, ast.BinOp):  # "X | None"
            return self._annotation_class(rel, ann.left)
        if not name:
            return None
        r = self.resolve_alias(rel, name)
        if r and r[0] == "class":
            return r[1]
        # annotation naming a class defined elsewhere in the project
        for ci in self.method_index.get("__init__", []):
            if ci.key[1] == name:
                return ci
        hits = [ci for ci in self.classes.values() if ci.key[1] == name]
        return hits[0] if len(hits) == 1 else None

    def class_method(self, ci: ClassInfo, name: str) -> FuncInfo | None:
        seen = set()
        stack = [ci]
        while stack:
            c = stack.pop()
            if c.key in seen:
                continue
            seen.add(c.key)
            m = c.methods.get(name)
            if m is not None:
                return m
            for b in c.bases:
                if isinstance(b, tuple) and b in self.classes:
                    stack.append(self.classes[b])
        return None

    # -- receiver typing ----------------------------------------------------
    def _scope_types(self, fi: FuncInfo) -> dict:
        """name -> ClassInfo key for the function's locals/params."""
        types: dict = {}
        if fi.cls is not None and fi.params and fi.params[0] == "self":
            types["self"] = (fi.rel, fi.cls)
        for a in fi.node.args.args + fi.node.args.kwonlyargs:
            if a.annotation is not None:
                t = self._annotation_class(fi.rel, a.annotation)
                if t is not None:
                    types[a.arg] = t.key
        for node in ast.walk(fi.node):
            tgt = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                t = self.expr_type(node.value, fi, types)
                if t is not None:
                    types.setdefault(tgt, t)
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                t = self._annotation_class(fi.rel, node.annotation)
                if t is not None:
                    types.setdefault(node.target.id, t.key)
        return types

    def expr_type(self, expr, fi: FuncInfo, types: dict):
        """Best-effort static type (a ClassInfo key) of an expression."""
        if isinstance(expr, ast.Name):
            return types.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self.expr_type(expr.value, fi, types)
            if base is not None and base in self.classes:
                return self.classes[base].attr_types.get(expr.attr)
            return None
        if isinstance(expr, ast.Call):
            if isinstance(expr.func, ast.Name):
                if expr.func.id == "getattr" and len(expr.args) >= 2 \
                        and isinstance(expr.args[1], ast.Constant):
                    base = self.expr_type(expr.args[0], fi, types)
                    if base is not None and base in self.classes:
                        return self.classes[base].attr_types.get(expr.args[1].value)
                    return None
                r = self.resolve_alias(fi.rel, expr.func.id)
                if r and r[0] == "class":
                    return r[1].key
        return None

    # -- edges --------------------------------------------------------------
    def _build_edges(self):
        for fi in self.funcs.values():
            types = self._scope_types(fi)
            fi._types = types  # reused by the passes
            fi._call_map = {}  # id(Call) -> FuncInfo, for the fact engine
            local_defs = {}
            parent = fi.node
            for sub in ast.walk(parent):
                if sub is not parent and isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = self._local_qname(fi, sub.name)
                    if q in self.funcs:
                        local_defs[sub.name] = self.funcs[q]
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.resolve_call(node, fi, types, local_defs)
                if callee is not None:
                    fi.callees.append((callee, node))
                    callee.callers.append(fi)
                    fi._call_map.setdefault(id(node), callee)
                # callbacks: a known function handed as an argument is
                # assumed invoked (pool.submit(run_task, ...), Thread target)
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Name):
                        cb = local_defs.get(arg.id)
                        if cb is None:
                            r = self.resolve_alias(fi.rel, arg.id)
                            cb = r[1] if r and r[0] == "func" else None
                        if cb is not None:
                            fi.callees.append((cb, node))
                            cb.callers.append(fi)

    def _local_qname(self, fi: FuncInfo, name: str) -> str:
        base = fi.qname.split("::", 1)[1]
        return f"{fi.rel}::{base}.<locals>.{name}"

    def resolve_call(self, call: ast.Call, fi: FuncInfo, types: dict,
                     local_defs: dict) -> FuncInfo | None:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in local_defs:
                return local_defs[f.id]
            r = self.resolve_alias(fi.rel, f.id)
            if r is None:
                return None
            if r[0] == "func":
                return r[1]
            if r[0] == "class":
                return self.class_method(r[1], "__init__")
            return None
        if isinstance(f, ast.Attribute):
            # module-attr call: dispatch.select(...)
            if isinstance(f.value, ast.Name):
                r = self.resolve_alias(fi.rel, f.value.id)
                if r and r[0] == "mod":
                    s = self.resolve_symbol(r[1], f.attr)
                    if s and s[0] == "func":
                        return s[1]
                    if s and s[0] == "class":
                        return self.class_method(s[1], "__init__")
                    return None
            t = self.expr_type(f.value, fi, types)
            if t is not None and t in self.classes:
                m = self.class_method(self.classes[t], f.attr)
                if m is not None:
                    return m
            # unique-name fallback: exactly one project class defines it
            owners = self.method_index.get(f.attr, ())
            if len(owners) == 1:
                return owners[0].methods[f.attr]
        return None

    # -- roots / reachability ----------------------------------------------
    def request_roots(self, extra=()) -> list[FuncInfo]:
        specs = tuple(REQUEST_ROOTS) + tuple(extra)
        out = []
        for fi in self.funcs.values():
            if fi.is_root:
                out.append(fi)
                continue
            for suffix, cls, name in specs:
                if fi.rel.endswith(suffix) and fi.name == name and fi.cls == cls:
                    out.append(fi)
        return out

    def boundaries(self) -> list[FuncInfo]:
        out = []
        for fi in self.funcs.values():
            if fi.is_boundary:
                out.append(fi)
                continue
            for suffix, cls, name in SESSION_BOUNDARIES:
                if fi.rel.endswith(suffix) and fi.name == name and fi.cls == cls:
                    out.append(fi)
        return out

    def reachable(self, roots) -> set:
        seen = set()
        stack = list(roots)
        while stack:
            fi = stack.pop()
            if fi.qname in seen:
                continue
            seen.add(fi.qname)
            for callee, _node in fi.callees:
                if callee.qname not in seen:
                    stack.append(callee)
        return seen


_GRAPH_MEMO: dict = {}


def graph_for(files: list[SourceFile]) -> CallGraph:
    """One CallGraph per distinct file-set revision — the three dataflow
    passes share it (building it is the expensive part)."""
    key = tuple(sorted((sf.rel, sf.sha) for sf in files))
    g = _GRAPH_MEMO.get(key)
    if g is None:
        _GRAPH_MEMO.clear()  # one live tree at a time; fixtures are tiny
        g = _GRAPH_MEMO[key] = CallGraph(files)
    return g


# ------------------------------------------------------- taint propagation

class TaintAnalysis:
    """Forward fact propagation from the request roots: REQ (request
    object) and TS (start_ts) flow through assignments, containers
    (coarse: a container holding a tainted value is tainted), attribute
    projection (`req.start_ts` -> TS) and call argument/return edges to a
    fixpoint."""

    def __init__(self, graph: CallGraph):
        self.g = graph
        roots = graph.request_roots()
        for fi in roots:
            for p in fi.params:
                seeded = set(_FACT_SEED_PARAMS.get(p, ()))
                t = fi._types.get(p)
                if t is not None and t[1].endswith("Request"):
                    seeded.add(REQ)
                if seeded:
                    fi.facts.setdefault(p, set()).update(seeded)
        # facts can only matter inside the request-path cone: every
        # reachable function gets analyzed at least once (so reachable
        # code has local_facts even before any taint arrives); changed
        # callees re-enter the worklist until the fixpoint
        reach = graph.reachable(roots)
        self._fixpoint([graph.funcs[q] for q in sorted(reach)])

    def _fixpoint(self, work: list):
        seen_rounds = 0
        while work and seen_rounds < 20000:
            seen_rounds += 1
            fi = work.pop()
            changed_callees = self._analyze(fi)
            work.extend(changed_callees)

    def _analyze(self, fi: FuncInfo) -> list:
        t = {p: set(fs) for p, fs in fi.facts.items()}
        for _ in range(2):  # loops: one extra sweep covers backward deps
            before = {k: set(v) for k, v in t.items()}
            self._walk_stmts(fi.node.body if hasattr(fi.node, "body") else [], fi, t)
            if t == before:
                break
        fi.local_facts = t
        # returns (a growing return-fact set re-queues the callers)
        rets = getattr(fi, "_returns", None)
        if rets is None:
            rets = fi._returns = [n.value for n in ast.walk(fi.node)
                                  if isinstance(n, ast.Return) and n.value is not None]
        ret: set = set()
        for value in rets:
            ret |= self.expr_facts(value, fi, t)
        changed = []
        if ret - fi.return_facts:
            fi.return_facts |= ret
            changed.extend(fi.callers)
        # propagate to callees
        for callee, call in fi.callees:
            if self._flow_call(fi, callee, call, t):
                changed.append(callee)
        return changed

    def _walk_stmts(self, stmts, fi, t):
        for node in stmts:
            if isinstance(node, ast.Assign):
                fx = self.expr_facts(node.value, fi, t)
                for tgt in node.targets:
                    self._bind(tgt, fx, t)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._bind(node.target, self.expr_facts(node.value, fi, t), t)
            elif isinstance(node, ast.AugAssign):
                fx = self.expr_facts(node.value, fi, t)
                self._bind(node.target, fx | self.expr_facts(node.target, fi, t), t)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._bind(node.target, self.expr_facts(node.iter, fi, t), t)
                self._walk_stmts(node.body, fi, t)
                self._walk_stmts(node.orelse, fi, t)
            elif isinstance(node, ast.While):
                self._walk_stmts(node.body, fi, t)
                self._walk_stmts(node.orelse, fi, t)
            elif isinstance(node, ast.If):
                self._walk_stmts(node.body, fi, t)
                self._walk_stmts(node.orelse, fi, t)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        self._bind(item.optional_vars,
                                   self.expr_facts(item.context_expr, fi, t), t)
                self._walk_stmts(node.body, fi, t)
            elif isinstance(node, ast.Try):
                self._walk_stmts(node.body, fi, t)
                for h in node.handlers:
                    self._walk_stmts(h.body, fi, t)
                self._walk_stmts(node.orelse, fi, t)
                self._walk_stmts(node.finalbody, fi, t)
            elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                # container mutation: L.append(x) / L.extend(x) / d.setdefault(...)
                call = node.value
                if isinstance(call.func, ast.Attribute) and call.func.attr in (
                        "append", "extend", "add", "insert", "setdefault", "update"):
                    fx = set()
                    for a in call.args:
                        fx |= self.expr_facts(a, fi, t)
                    root = call.func.value
                    while isinstance(root, (ast.Attribute, ast.Call, ast.Subscript)):
                        root = getattr(root, "value", None) or getattr(root, "func", None)
                        if root is None:
                            break
                    if isinstance(root, ast.Name) and fx:
                        t.setdefault(root.id, set()).update(fx)

    def _bind(self, tgt, fx: set, t: dict):
        if isinstance(tgt, ast.Name):
            if fx:
                t.setdefault(tgt.id, set()).update(fx)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._bind(e, fx, t)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, fx, t)

    def expr_facts(self, expr, fi, t) -> set:
        if isinstance(expr, ast.Name):
            return set(t.get(expr.id, ()))
        if isinstance(expr, ast.Attribute):
            base = self.expr_facts(expr.value, fi, t)
            if REQ in base and expr.attr == "start_ts":
                return base | {TS}
            return base
        if isinstance(expr, ast.Call):
            # resolved project call: constructor re-wraps, function returns
            callee = getattr(fi, "_call_map", {}).get(id(expr))
            arg_facts: set = set()
            for a in list(expr.args) + [k.value for k in expr.keywords]:
                arg_facts |= self.expr_facts(a, fi, t)
            if callee is not None and callee.name == "__init__" and arg_facts:
                return {REQ} if (REQ in arg_facts or TS in arg_facts) else set()
            if callee is not None:
                return set(callee.return_facts)
            # unresolved: coarse — taint of receiver and args flows through
            out = set(arg_facts)
            if isinstance(expr.func, ast.Attribute):
                out |= self.expr_facts(expr.func.value, fi, t)
            return out
        out: set = set()
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, (ast.expr, ast.keyword)):
                sub = child.value if isinstance(child, ast.keyword) else child
                out |= self.expr_facts(sub, fi, t)
        return out

    def _flow_call(self, fi, callee, call, t) -> bool:
        params = list(callee.params)
        if params and params[0] == "self" and not (
                isinstance(call.func, ast.Name) and call.func.id == callee.name):
            params = params[1:]
        changed = False
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred) or i >= len(params):
                break
            fx = self.expr_facts(a, fi, t)
            if fx - callee.facts.get(params[i], set()):
                callee.facts.setdefault(params[i], set()).update(fx)
                changed = True
        for kw in call.keywords:
            if kw.arg is None or kw.arg not in callee.params:
                continue
            fx = self.expr_facts(kw.value, fi, t)
            if fx - callee.facts.get(kw.arg, set()):
                callee.facts.setdefault(kw.arg, set()).update(fx)
                changed = True
        return changed


# ------------------------------------------------------- pass: snapshot

_LATEST_CALLS = {"max_ts", "next_ts", "max_committed", "latest_ts"}


def _walk_own(root):
    """ast.walk, but nested def bodies stay out: they are separate
    FuncInfos walked on their own — re-walking them from the parent
    would double-report every finding inside a closure. Lambdas are NOT
    FuncInfos, so their bodies stay in."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


def _closure_facts(graph: CallGraph, fi: FuncInfo) -> dict:
    """The function's fact map, with the enclosing function's facts as a
    fallback for closures (captured names carry the parent's taint)."""
    t = dict(fi.local_facts)
    if ".<locals>." in fi.qname:
        parent_q = fi.qname.rsplit(".<locals>.", 1)[0]
        parent = graph.funcs.get(parent_q)
        if parent is not None:
            for k, v in parent.local_facts.items():
                t.setdefault(k, v)
    return t


def _is_kv_receiver(graph, expr, fi, types) -> bool:
    """Receiver is the MVCC engine: typed as a class named MemKV, or a
    syntactic `.kv` attribute chain (fixtures without full typing)."""
    t = graph.expr_type(expr, fi, types)
    if t is not None and t[1] == "MemKV":
        return True
    if isinstance(expr, ast.Attribute) and expr.attr == "kv":
        return True
    return isinstance(expr, ast.Name) and expr.id == "kv"


def _ts_argument(call: ast.Call, method: str):
    idx = {"get": 1, "scan": 2}[method]
    for kw in call.keywords:
        if kw.arg == "ts":
            return kw.value
    if len(call.args) > idx:
        a = call.args[idx]
        return None if isinstance(a, ast.Starred) else a
    return None


def _is_latest_version_expr(expr, graph, fi) -> bool:
    """ts argument that structurally means "newest version": a literal,
    a *_MAX_* constant, or a max_ts()/next_ts()-style oracle call."""
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Name) and ("MAX" in expr.id.upper() or expr.id.isupper()):
        return True
    if isinstance(expr, ast.Call):
        f = expr.func
        name = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", "")
        return name in _LATEST_CALLS
    return False


def run_snapshot(files: list[SourceFile]) -> list:
    graph = graph_for(files)
    roots = graph.request_roots()
    if not roots:
        return []
    taint = TaintAnalysis(graph)
    reachable = graph.reachable(roots)
    findings: list = []
    for qname in sorted(reachable):
        fi = graph.funcs[qname]
        if os.sep + "analysis" + os.sep in fi.rel or "/analysis/" in fi.rel:
            continue
        types = fi._types
        t = _closure_facts(graph, fi)
        for node in _walk_own(fi.node):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            meth = node.func.attr
            if meth in ("max_ts", "latest_ts") and _is_kv_receiver(
                    graph, node.func.value, fi, types):
                findings.append(Finding(
                    fi.rel, node.lineno, PASS_SNAPSHOT,
                    f"`{meth}()` on a request path reads the NEWEST version, not the "
                    f"statement snapshot — MVCC reads reachable from dispatch must "
                    f"flow the request's start_ts"))
                continue
            if meth not in ("get", "scan") or not _is_kv_receiver(
                    graph, node.func.value, fi, types):
                continue
            ts_arg = _ts_argument(node, meth)
            if ts_arg is None:
                findings.append(Finding(
                    fi.rel, node.lineno, PASS_SNAPSHOT,
                    f"`kv.{meth}` on a request path without a snapshot ts — every "
                    f"MVCC read reachable from dispatch must flow the request's start_ts"))
                continue
            if _is_latest_version_expr(ts_arg, graph, fi):
                findings.append(Finding(
                    fi.rel, node.lineno, PASS_SNAPSHOT,
                    f"`kv.{meth}` on a request path reads at a latest-version ts "
                    f"({ast.unparse(ts_arg)}) — a raw newest-version read bypasses "
                    f"the statement snapshot; flow the request's start_ts instead"))
                continue
            if not (taint.expr_facts(ts_arg, fi, t) & {TS, REQ}):
                findings.append(Finding(
                    fi.rel, node.lineno, PASS_SNAPSHOT,
                    f"`kv.{meth}` ts argument `{ast.unparse(ts_arg)}` does not flow "
                    f"from the request's start_ts (no REQ/TS fact reaches it) — "
                    f"snapshot discipline broken on a request path"))
    return findings


# ------------------------------------------------------- pass: backoff

def _consults_backoff_directly(fi: FuncInfo, node=None) -> bool:
    scope = node if node is not None else fi.node
    for sub in ast.walk(scope):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
            if sub.func.attr in ("backoff", "sleep"):
                recv = sub.func.value
                name = recv.id if isinstance(recv, ast.Name) else \
                    recv.attr if isinstance(recv, ast.Attribute) else ""
                if "boff" in name or "backoff" in name:
                    return True
        if isinstance(sub, ast.Raise) and isinstance(sub.exc, ast.Call) \
                and isinstance(sub.exc.func, ast.Name) \
                and "Backoff" in sub.exc.func.id:
            return True
    return False


def _compute_backoff_consulters(graph: CallGraph) -> None:
    for fi in graph.funcs.values():
        fi.consults_backoff = _consults_backoff_directly(fi)
    changed = True
    while changed:
        changed = False
        for fi in graph.funcs.values():
            if fi.consults_backoff:
                continue
            if any(c.consults_backoff for c, _ in fi.callees):
                fi.consults_backoff = True
                changed = True


def _is_retry_loop(loop: ast.While) -> bool:
    """An UNBOUNDED re-attempt loop: `while True:` (or another constant-
    true test) that `continue`s back around. A `while i < n:` walk with a
    continue is an iteration idiom, not a retry — and a bounded retry
    loop consumes its attempt budget by construction."""
    t = loop.test
    unbounded = isinstance(t, ast.Constant) and bool(t.value)
    return unbounded and _loop_has_continue(loop)


def _loop_has_continue(loop: ast.While) -> bool:
    """Continue belonging to THIS loop (nested loops own their own)."""
    def walk(stmts):
        for node in stmts:
            if isinstance(node, ast.Continue):
                return True
            if isinstance(node, (ast.While, ast.For, ast.AsyncFor,
                                 ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for attr in ("body", "orelse", "finalbody"):
                if walk(getattr(node, attr, [])):
                    return True
            if isinstance(node, ast.Try) and any(walk(h.body) for h in node.handlers):
                return True
        return False
    return walk(loop.body)


def _loop_consults_budget(graph, fi, loop) -> bool:
    if _consults_backoff_directly(fi, loop):
        return True
    calls_in_loop = {id(c) for c in ast.walk(loop) if isinstance(c, ast.Call)}
    for callee, call in fi.callees:
        if id(call) in calls_in_loop and callee.consults_backoff:
            return True
    return False


def _is_time_sleep(call: ast.Call, graph: CallGraph, fi: FuncInfo) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "sleep" and isinstance(f.value, ast.Name):
        imp = graph.imports.get(fi.rel, {}).get(f.value.id)
        return bool(imp and imp[0] == "mod" and imp[1] == "time")
    if isinstance(f, ast.Name) and f.id == "sleep":
        imp = graph.imports.get(fi.rel, {}).get("sleep")
        return bool(imp and imp[0] == "sym" and imp[1] == "time")
    return False


def run_backoff(files: list[SourceFile]) -> list:
    graph = graph_for(files)
    roots = graph.request_roots(extra=CDC_ROOTS + COLUMNAR_ROOTS + FRONT_DOOR_ROOTS + TOPSQL_ROOTS + MPP_ROOTS + PITR_ROOTS)
    if not roots:
        return []
    _compute_backoff_consulters(graph)
    reachable = graph.reachable(roots)
    findings: list = []
    for qname in sorted(reachable):
        fi = graph.funcs[qname]
        if fi.rel.endswith(os.path.join("util", "backoff.py")) or \
                fi.rel.endswith("util/backoff.py"):
            continue  # the Backoffer IS the sliced/clamped sleep primitive
        for node in _walk_own(fi.node):
            if isinstance(node, ast.While) and _is_retry_loop(node):
                if not _loop_consults_budget(graph, fi, node):
                    findings.append(Finding(
                        fi.rel, node.lineno, PASS_BACKOFF,
                        "retry loop on a request path never consults a Backoffer "
                        "budget — a persistent fault spins this loop forever "
                        "instead of surfacing a typed RegionUnavailableError"))
            elif isinstance(node, ast.Call) and _is_time_sleep(node, graph, fi):
                findings.append(Finding(
                    fi.rel, node.lineno, PASS_BACKOFF,
                    "raw time.sleep on a request path — sleeps must ride "
                    "Backoffer.sleep (sliced for KILL QUERY, clamped to the "
                    "statement deadline, attributed to backoff metrics)"))
    return findings


# ------------------------------------------------- pass: error escape

_BARE_RAISES = {"RuntimeError", "Exception"}


def _builtin_exc(name: str):
    obj = getattr(builtins, name, None)
    return obj if isinstance(obj, type) and issubclass(obj, BaseException) else None


class EscapeAnalysis:
    """Per-function escaping exception sets to a fixpoint: a raise (or a
    callee's escape) survives the enclosing handler stack unless a
    handler absorbs it; a handler whose body ends in a TOP-LEVEL bare
    `raise` re-raises, so it is transparent (the session.execute shape:
    catch Exception, map the typed ones, re-raise the rest)."""

    def __init__(self, graph: CallGraph):
        self.g = graph
        self._sub_memo: dict = {}
        # escape only matters in the cone of the roots and the boundary
        reach = graph.reachable(
            graph.request_roots(extra=ESCAPE_EXTRA_ROOTS + CDC_ROOTS + COLUMNAR_ROOTS + FRONT_DOOR_ROOTS + FRONT_DOOR_ESCAPE_ROOTS + TOPSQL_ROOTS + MPP_ROOTS + COALESCE_ROOTS + PITR_ROOTS)
            + graph.boundaries())
        work = [graph.funcs[q] for q in sorted(reach)]
        rounds = 0
        while work and rounds < 20000:
            rounds += 1
            fi = work.pop()
            if self._analyze(fi):
                work.extend(c for c in fi.callers)

    # -- type lattice -------------------------------------------------------
    def exc_class(self, rel: str, expr):
        """Resolve a raise/handler type expression to a ClassInfo key or
        a builtin exception name."""
        name = None
        if isinstance(expr, ast.Call):
            expr = expr.func
        if isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Attribute):
            name = expr.attr
        if name is None:
            return None
        r = self.g.resolve_alias(rel, name)
        if r and r[0] == "class":
            return r[1].key
        if _builtin_exc(name) is not None:
            return name
        hits = [ci for ci in self.g.classes.values() if ci.key[1] == name]
        return hits[0].key if len(hits) == 1 else name

    def _bases_of(self, t):
        if isinstance(t, tuple):
            ci = self.g.classes.get(t)
            return ci.bases if ci else []
        b = _builtin_exc(t)
        return [b.__bases__[0].__name__] if b and b.__bases__ else []

    def is_subtype(self, t, handler) -> bool:
        memo_key = (t, handler)
        hit = self._sub_memo.get(memo_key)
        if hit is not None:
            return hit
        r = self._is_subtype(t, handler)
        self._sub_memo[memo_key] = r
        return r

    def _is_subtype(self, t, handler) -> bool:
        if handler is None:
            return True  # bare except
        if isinstance(handler, str) and _builtin_exc(handler) in (Exception, BaseException):
            return True
        seen = set()
        stack = [t]
        while stack:
            cur = stack.pop()
            key = cur if isinstance(cur, str) else cur
            if key in seen:
                continue
            seen.add(key)
            if cur == handler:
                return True
            if isinstance(cur, str) and isinstance(handler, str):
                a, b = _builtin_exc(cur), _builtin_exc(handler)
                if a is not None and b is not None and issubclass(a, b):
                    return True
            stack.extend(self._bases_of(cur))
        return False

    # -- per-function -------------------------------------------------------
    @staticmethod
    def _handler_transparent(handler: ast.ExceptHandler) -> bool:
        """Top-level unconditional bare `raise` in the handler body
        re-raises what it caught; a CONDITIONAL bare raise (the
        cop-debug-raise gate shape) is a deliberate opt-in, treated as
        absorbing."""
        return any(isinstance(s, ast.Raise) and s.exc is None for s in handler.body)

    def _survives(self, t, handler_stack) -> bool:
        """Walk the enclosing trys innermost-out: the first handler per
        level that matches either absorbs (done) or — if transparent —
        re-raises to the NEXT outer level."""
        for handlers in reversed(handler_stack):
            for h in handlers:
                if h.type is None:
                    types = [None]
                elif isinstance(h.type, ast.Tuple):
                    types = list(h.type.elts)
                else:
                    types = [h.type]
                matched = False
                for ht in types:
                    hk = None if ht is None else self.exc_class(self._rel, ht)
                    if hk is None and ht is not None:
                        continue
                    if self.is_subtype(t, hk):
                        matched = True
                        break
                if matched:
                    if self._handler_transparent(h):
                        break  # re-raised: continue to the outer level
                    return False  # absorbed
            # no handler at this level caught it (or it was re-raised)
        return True

    def _prepare(self, fi: FuncInfo) -> list:
        """One-time site extraction: every raise and every resolved call,
        each with its (static) enclosing handler stack. Re-analysis then
        never touches the AST again — it just re-filters callee escape
        sets through the precomputed stacks."""
        callees_at: dict = {}
        for callee, call in fi.callees:
            callees_at.setdefault(id(call), []).append(callee)
        sites: list = []

        def calls_in(expr, stack):
            if expr is None:
                return
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Call):
                    for callee in callees_at.get(id(sub), ()):
                        sites.append(("call", callee, None, 0, stack))

        def walk(stmts, stack):
            for node in stmts:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(node, ast.Raise):
                    if node.exc is not None:
                        t = self.exc_class(fi.rel, node.exc)
                        if t is not None:
                            sites.append(("raise", t, fi.rel, node.lineno, stack))
                        calls_in(node.exc, stack)
                elif isinstance(node, ast.Try):
                    walk(node.body, stack + (node.handlers,))
                    for h in node.handlers:
                        walk(h.body, stack)
                    walk(node.orelse, stack)  # orelse escapes bypass the handlers
                    walk(node.finalbody, stack)
                elif isinstance(node, ast.If):
                    calls_in(node.test, stack)
                    walk(node.body, stack)
                    walk(node.orelse, stack)
                elif isinstance(node, ast.While):
                    calls_in(node.test, stack)
                    walk(node.body, stack)
                    walk(node.orelse, stack)
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    calls_in(node.iter, stack)
                    walk(node.body, stack)
                    walk(node.orelse, stack)
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        calls_in(item.context_expr, stack)
                    walk(node.body, stack)
                else:
                    calls_in(node, stack)

        walk(fi.node.body, ())
        return sites

    def _analyze(self, fi: FuncInfo) -> bool:
        """Escape sets are deduplicated per exception TYPE: one
        representative origin site rides along for the report (keeps the
        fixpoint linear in #types instead of #raise-sites)."""
        self._rel = fi.rel
        sites = getattr(fi, "_esc_sites", None)
        if sites is None:
            sites = fi._esc_sites = self._prepare(fi)
        memo = getattr(fi, "_survive_memo", None)
        if memo is None:
            memo = fi._survive_memo = {}
        out: dict = {}
        for kind, payload, rel, line, stack in sites:
            if kind == "raise":
                if payload not in out:
                    key = (payload, id(stack))
                    ok = memo.get(key)
                    if ok is None:
                        ok = memo[key] = self._survives(payload, stack)
                    if ok:
                        out[payload] = (rel, line)
            else:
                for t, site in payload.escapes.items():
                    if t not in out:
                        key = (t, id(stack))
                        ok = memo.get(key)
                        if ok is None:
                            ok = memo[key] = self._survives(t, stack)
                        if ok:
                            out[t] = site
        if set(out) - set(fi.escapes):
            for t, site in out.items():
                fi.escapes.setdefault(t, site)
            return True
        return False


def _family_classes(graph: CallGraph) -> set:
    """Typed request-path error classes: Exception subclasses defined in
    the dispatch/store/replication/backoff layers (live tree), or any
    project exception class in a fixture file set."""
    fam: set = set()
    live = any(sf.rel.startswith("tidb_tpu") for sf in graph.files)
    for key, ci in graph.classes.items():
        # exception-ness: transitively rooted in a builtin exception
        stack, seen, is_exc = [key], set(), False
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            if isinstance(cur, str) and _builtin_exc(cur) is not None:
                is_exc = True
                break
            if isinstance(cur, tuple) and cur in graph.classes:
                stack.extend(graph.classes[cur].bases)
        if not is_exc:
            continue
        rel = ci.rel.replace(os.sep, "/")
        in_family = any(f"tidb_tpu/{d}/" in rel for d in _FAMILY_DIRS) or \
            any(rel.endswith(f) for f in _FAMILY_FILES)
        if in_family or not live:
            fam.add(key)
    return fam


def _mapped_types(graph: CallGraph, boundary: FuncInfo) -> set:
    """Exception type NAMES the boundary module maps to SQLError: except
    handlers whose body raises SQLError, and isinstance(exc, T) branches
    doing the same."""
    sf = graph.by_rel.get(boundary.rel)
    mapped: set = set()
    if sf is None or sf.tree is None:
        return mapped

    def names_of(expr):
        if isinstance(expr, ast.Name):
            return [expr.id]
        if isinstance(expr, ast.Attribute):
            return [expr.attr]
        if isinstance(expr, ast.Tuple):
            return [n for e in expr.elts for n in names_of(e)]
        return []

    def raises_sqlerror(stmts) -> bool:
        for s in stmts:
            for sub in ast.walk(s):
                if isinstance(sub, ast.Raise) and isinstance(sub.exc, ast.Call) \
                        and isinstance(sub.exc.func, ast.Name) \
                        and sub.exc.func.id == "SQLError":
                    return True
        return False

    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ExceptHandler) and node.type is not None:
            if raises_sqlerror(node.body):
                mapped.update(names_of(node.type))
        elif isinstance(node, ast.If):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                        and sub.func.id == "isinstance" and len(sub.args) == 2:
                    if raises_sqlerror(node.body):
                        mapped.update(names_of(sub.args[1]))
    return mapped


def run_escape(files: list[SourceFile]) -> list:
    graph = graph_for(files)
    roots = graph.request_roots(extra=ESCAPE_EXTRA_ROOTS + CDC_ROOTS + COLUMNAR_ROOTS + FRONT_DOOR_ROOTS + FRONT_DOOR_ESCAPE_ROOTS + TOPSQL_ROOTS + MPP_ROOTS + COALESCE_ROOTS + PITR_ROOTS)
    boundaries = graph.boundaries()
    if not roots and not boundaries:
        return []
    esc = EscapeAnalysis(graph)
    findings: list = []
    seen: set = set()
    # (a) bare RuntimeError/Exception escaping a request root
    for fi in roots:
        for t, (rel, line) in sorted(fi.escapes.items(), key=str):
            if isinstance(t, str) and t in _BARE_RAISES and (rel, line) not in seen:
                seen.add((rel, line))
                findings.append(Finding(
                    rel, line, PASS_ESCAPE,
                    f"bare `raise {t}` escapes the request path uncaught (reaches "
                    f"{fi.name}) — use a typed error from store/errors.py or a "
                    f"subsystem exception with a MySQL code mapping so dispatch "
                    f"can classify, back off and account it"))
    # (b) typed family errors escaping the session boundary unmapped. A
    # handler/isinstance mapping of a BASE class covers its subclasses
    # (except TxnError absorbs KeyIsLocked).
    fam = _family_classes(graph)
    for b in boundaries:
        mapped = _mapped_types(graph, b)
        for t, (rel, line) in sorted(b.escapes.items(), key=str):
            if not isinstance(t, tuple) or t not in fam:
                continue
            name = t[1]
            covered = name in mapped or any(
                esc.is_subtype(t, m) for m in
                (esc.exc_class(b.rel, ast.Name(id=mn)) for mn in mapped) if m)
            if name == "SQLError" or covered or (rel, line, name) in seen:
                continue
            seen.add((rel, line, name))
            findings.append(Finding(
                rel, line, PASS_ESCAPE,
                f"typed error {name} (raised here) escapes the session boundary "
                f"{b.name}() with no SQLError mapping — add an except/isinstance "
                f"mapping with a MySQL error code before it reaches the client"))
    # (c) the lexical floor the old error-taxonomy pass provided: bare
    # RuntimeError/Exception raises in the dispatch/store/PD layers are
    # findings even OUTSIDE the request cone (control-plane code — PD
    # ticks, schedulers — still deserves typed errors; interprocedural
    # reachability must narrow nothing the lexical rule guaranteed)
    for sf in graph.files:
        rel = sf.rel.replace(os.sep, "/")
        if not any(rel.startswith(f"tidb_tpu/{d}/") for d in ("distsql", "store", "pd", "cdc", "columnar", "mpp", "br")):
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Raise) and node.exc is not None):
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in _BARE_RAISES and (sf.rel, node.lineno) not in seen:
                seen.add((sf.rel, node.lineno))
                findings.append(Finding(
                    sf.rel, node.lineno, PASS_ESCAPE,
                    f"bare `raise {name}` in a dispatch/store/PD layer — use a "
                    f"typed error from store/errors.py (or a subsystem exception "
                    f"with a MySQL code mapping) so callers can classify it"))
    return findings


