"""`jax-audit` — trace the exec builder's compiled programs to closed
jaxprs and walk them for device-hostile patterns (ISSUE 9; ref: the
reference audits its pushed-down executors with plan tests — here the
"plan" is the jaxpr XLA will compile, so the audit walks that).

A catalog of representative DAG programs — one per exec-op builder path
(selection, hash aggregation, stream aggregation, topn, hash join), each
traced BOTH single-region and vmap-batched — goes through four checks:

  * **float64 leaks** — the catalog's columns are all integers, so any
    f64/c128 appearing in the jaxpr was INTRODUCED by the program (a
    Python float promotion, a stray true-divide, an astype): on TPU that
    means software-emulated arithmetic on the hot path. Programs with
    real DOUBLE columns legitimately carry f64 (MySQL semantics); the
    audit pins the *int-only* programs where any f64 is a leak.
  * **host callbacks / transfers inside jit** — pure_callback and
    friends serialize every launch through the host; device_put inside a
    traced program is a transfer the donor should have done outside.
  * **vmap axis consistency** — every output of the region-batched
    variant must carry the leading region axis (size B) over the single
    variant's shape with the same dtype; a dropped/reordered axis means
    region results silently alias each other.
  * **trace stability** — building the same program twice must produce
    byte-identical jaxprs. A closure-captured Python scalar (a counter,
    a timestamp, an id()) bakes a different constant each build: every
    ProgramCache miss then compiles a NEW entry (the cache key can't see
    the closure), silently multiplying entries and compile time. Large
    baked consts (>4 KiB) are flagged for the same reason: operand data
    belongs in arguments, not in the program.

Fixture mode (`--files`): a fixture module exports `JAX_AUDIT_CATALOG`,
a list of `{"name": str, "make": callable}` entries where `make()`
returns `(fn, args)`; each is traced through the same checks.
"""

from __future__ import annotations

import importlib.util
import os
import sys

from .common import Finding

PASS = "jax-audit"

# where live findings anchor: the program builder is the artifact under audit
_BUILDER_REL = os.path.join("tidb_tpu", "exec", "builder.py")

_HOST_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call", "infeed", "outfeed",
    "device_put",
}

_CONST_LIMIT_BYTES = 4096

_VMAP_BATCH = 3
_CAPACITY = 8
_RADIX_CAPACITY = 512  # probe capacity satisfying the radix ratio gate
_GROUP_CAPACITY = 16


# ----------------------------------------------------------- jaxpr walking

def iter_eqns(jaxpr):
    """Every eqn in a (closed) jaxpr, recursing through call primitives
    (pjit/closed_call), scan/while carries and cond branches."""
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            yield from _iter_sub(p)


def _iter_sub(p):
    if hasattr(p, "eqns"):  # a Jaxpr
        yield from iter_eqns(p)
    elif hasattr(p, "jaxpr"):  # a ClosedJaxpr
        yield from iter_eqns(p.jaxpr)
    elif isinstance(p, (list, tuple)):
        for q in p:
            yield from _iter_sub(q)


def _avals_of(eqn):
    for v in list(eqn.invars) + list(eqn.outvars):
        av = getattr(v, "aval", None)
        if av is not None and hasattr(av, "dtype"):
            yield av


def _wide_float(dtype) -> bool:
    s = str(dtype)
    return s in ("float64", "complex128")


def audit_jaxpr(name: str, closed, anchor: tuple) -> list:
    """f64-leak + host-callback checks over one closed jaxpr. `anchor`
    is the (rel, line) findings attach to."""
    rel, line = anchor
    findings: list = []
    f64_prims: dict = {}
    host_prims: dict = {}
    for eqn in iter_eqns(closed.jaxpr):
        pname = eqn.primitive.name
        if pname in _HOST_PRIMITIVES:
            host_prims.setdefault(pname, 0)
            host_prims[pname] += 1
        for av in _avals_of(eqn):
            if _wide_float(av.dtype):
                f64_prims.setdefault(pname, 0)
                f64_prims[pname] += 1
                break
    # leaks only count when no INPUT carried the wide type (real DOUBLE
    # columns legitimately flow f64 end to end)
    in_wide = any(_wide_float(getattr(av, "dtype", ""))
                  for av in closed.in_avals if hasattr(av, "dtype"))
    if f64_prims and not in_wide:
        prims = ", ".join(sorted(f64_prims))
        findings.append(Finding(
            rel, line, PASS,
            f"program {name!r}: float64 leaked into an integer-only program "
            f"(primitives: {prims}) — on TPU this is software-emulated math; "
            f"find the Python float / true-divide / astype that promoted"))
    for pname, n in sorted(host_prims.items()):
        findings.append(Finding(
            rel, line, PASS,
            f"program {name!r}: host primitive `{pname}` x{n} inside the "
            f"jitted program — every launch round-trips through the host; "
            f"hoist it out of the traced computation"))
    for i, c in enumerate(getattr(closed, "consts", ()) or ()):
        nbytes = getattr(c, "nbytes", 0)
        if nbytes and nbytes > _CONST_LIMIT_BYTES:
            findings.append(Finding(
                rel, line, PASS,
                f"program {name!r}: baked constant #{i} is {nbytes} bytes — "
                f"closure-captured operand data recompiles (and re-uploads) "
                f"per build; pass it as a program argument instead"))
    return findings


def audit_stability(name: str, make, anchor: tuple) -> tuple:
    """Trace `make()` twice; differing jaxprs mean a closure-captured
    value changed between builds. Returns (findings, first_closed_jaxpr,
    args) so callers reuse the trace."""
    import jax

    rel, line = anchor
    fn1, args1 = make()
    fn2, args2 = make()
    jx1 = jax.make_jaxpr(fn1)(*args1)
    jx2 = jax.make_jaxpr(fn2)(*args2)
    findings: list = []
    if str(jx1) != str(jx2):
        findings.append(Finding(
            rel, line, PASS,
            f"program {name!r}: two identical builds traced to DIFFERENT "
            f"jaxprs — a closure-captured Python scalar (counter, timestamp, "
            f"id) is baked into the trace; every build multiplies "
            f"ProgramCache entries with programs the cache key cannot tell "
            f"apart"))
    return findings, jx1, args1


# ----------------------------------------------------------- live catalog

def _int_chunk(n: int = 6):
    from ..chunk import Chunk
    from ..types import Datum, new_longlong

    I = new_longlong()
    rows = [[Datum.i64(i % 3), Datum.i64(i * 7 % 11)] for i in range(n)]
    return Chunk.from_rows([I, I], rows), I


def _scan(table_id: int, I):
    from ..exec.dag import ColumnInfo, TableScan

    return TableScan(table_id, (ColumnInfo(1, I), ColumnInfo(2, I)))


def live_catalog() -> list:
    """(name, dag, n_batches) for every exec-op builder path — the
    acceptance set: selection, hashagg, streamagg, topn, hashjoin."""
    from ..exec.dag import Aggregation, ColumnInfo, DAGRequest, Join, Selection, TableScan, TopN
    from ..expr import AggDesc, col, func, lit

    _ch, I = _int_chunk()
    scan = _scan(31, I)
    sel = DAGRequest(
        (scan, Selection((func("gt", I, col(1, I), lit(2, I)),))),
        output_offsets=(0, 1))
    hashagg = DAGRequest(
        (scan, Aggregation(group_by=(col(0, I),),
                           aggs=(AggDesc("sum", (col(1, I),)),
                                 AggDesc("count", (col(1, I),))))),
        output_offsets=(0, 1, 2))
    streamagg = DAGRequest(
        (scan, Aggregation(group_by=(col(0, I),),
                           aggs=(AggDesc("max", (col(1, I),)),), stream=True)),
        output_offsets=(0, 1))
    topn = DAGRequest(
        (scan, TopN(order_by=((col(1, I), True),), limit=4)),
        output_offsets=(0, 1))
    join = DAGRequest(
        (scan, Join(build=(_scan(32, I),), probe_keys=(col(0, I),),
                    build_keys=(col(0, I),), join_type="inner")),
        output_offsets=(0, 1, 2, 3))
    # the radix-partitioned join path (ISSUE 13): planner-proven unique
    # build + int keys routes through ops/radix_join.py when the
    # build/probe capacity ratio passes — the probe batch is padded wide
    # (RADIX_CAPACITY) so the gate holds at catalog scale; the grouped
    # tail makes the mesh variant ("group" kind) trace too
    radix_join = DAGRequest(
        (TableScan(33, (ColumnInfo(1, I), ColumnInfo(2, I))),
         Join(build=(_scan(34, I),), probe_keys=(col(0, I),),
              build_keys=(col(0, I),), join_type="inner",
              build_unique=True),
         Aggregation(group_by=(col(1, I),),
                     aggs=(AggDesc("sum", (col(2, I),)),), partial=True)),
        output_offsets=(0, 1))
    # partial-mode shapes: what the dispatch planner's MESH tier runs —
    # audited as shard_map programs too (mesh_merge_kind gates which)
    partial_scalar = DAGRequest(
        (scan, Aggregation(group_by=(),
                           aggs=(AggDesc("sum", (col(1, I),)),
                                 AggDesc("count", ())), partial=True)),
        output_offsets=(0, 1))
    partial_hashagg = DAGRequest(
        (scan, Aggregation(group_by=(col(0, I),),
                           aggs=(AggDesc("sum", (col(1, I),)),
                                 AggDesc("count", ())), partial=True)),
        output_offsets=(0, 1, 2))
    # the columnar-replica scan shape (ISSUE 12): the WHOLE logical plan
    # — scan -> selection -> complete aggregation — runs as one program
    # over the replica's device-resident stable chunk (columnar/route.py
    # `_run`), no partial/final split, no region axis
    columnar_scan = DAGRequest(
        (scan, Selection((func("gt", I, col(1, I), lit(2, I)),)),
         Aggregation(group_by=(col(0, I),),
                     aggs=(AggDesc("sum", (col(1, I),)),
                           AggDesc("count", ())))),
        output_offsets=(0, 1, 2))
    return [
        ("selection", sel, 1, None),
        ("hashagg", hashagg, 1, None),
        ("streamagg", streamagg, 1, None),
        ("topn", topn, 1, None),
        ("hashjoin", join, 2, None),
        # probe batch padded wide so the radix build/probe ratio gate
        # holds — the trace goes through ops/radix_join.py, not the
        # monolithic kernel (assert: its program carries no 4-operand
        # merge sort; the audit checks f64/host/consts/stability)
        ("radix_join", radix_join, 2, (_RADIX_CAPACITY, _CAPACITY)),
        ("partial_scalar_agg", partial_scalar, 1, None),
        ("partial_hashagg", partial_hashagg, 1, None),
        ("columnar_scan", columnar_scan, 1, None),
    ]


def _entry_caps(n_batches: int, caps) -> tuple:
    return tuple(caps) if caps else tuple(_CAPACITY for _ in range(n_batches))


def _batches(n_batches: int, vmap: bool, caps=None):
    from ..chunk import to_device_batch
    from ..chunk.device import to_stacked_device_batch

    caps = _entry_caps(n_batches, caps)
    ch, _I = _int_chunk()
    if vmap:
        probe = to_stacked_device_batch([ch] * _VMAP_BATCH, caps[0])
    else:
        probe = to_device_batch(ch, capacity=caps[0])
    aux = [to_device_batch(ch, capacity=c) for c in caps[1:]]
    return [probe] + aux


def _make_builder(dag, n_batches: int, vmap: bool, caps=None):
    """A `make` thunk for audit_stability: a fresh build_program each
    call — exactly what a ProgramCache miss does."""
    from ..exec.builder import build_program

    def make():
        cd = build_program(
            dag, _entry_caps(n_batches, caps),
            group_capacity=_GROUP_CAPACITY,
            vmap_batch=_VMAP_BATCH if vmap else None)
        return cd.fn, _batches(n_batches, vmap, caps)
    return make


_LIVE_MEMO: list | None = None


def audit_live() -> list:
    """Trace the whole catalog (single + vmapped) through every check.
    Memoized per process — the catalog is deterministic and the traces
    are the expensive part."""
    global _LIVE_MEMO
    if _LIVE_MEMO is not None:
        return list(_LIVE_MEMO)
    anchor = (_BUILDER_REL.replace(os.sep, "/"), 1)
    findings: list = []
    import jax

    for name, dag, n_batches, caps in live_catalog():
        single_out = None
        for vmap in (False, True):
            variant = f"{name}/{'vmap' if vmap else 'single'}"
            make = _make_builder(dag, n_batches, vmap, caps)
            try:
                if vmap:
                    # the stability double-build already ran on the single
                    # variant (same builder, same closures) — the vmapped
                    # trace runs once, for the axis + jaxpr checks
                    fn, args = make()
                    closed = jax.make_jaxpr(fn)(*args)
                    fs = []
                else:
                    fs, closed, _args = audit_stability(variant, make, anchor)
            except Exception as exc:  # noqa: BLE001 — a trace failure IS a finding
                findings.append(Finding(
                    anchor[0], anchor[1], PASS,
                    f"program {variant!r} failed to trace: {exc}"))
                continue
            findings.extend(fs)
            findings.extend(audit_jaxpr(variant, closed, anchor))
            if not vmap:
                single_out = closed.out_avals
            else:
                findings.extend(_check_vmap_axis(name, single_out, closed.out_avals, anchor))
        findings.extend(_audit_mesh_variant(name, dag, n_batches, anchor, caps))
    findings.extend(_audit_exchange_variant(anchor))
    _LIVE_MEMO = list(findings)
    return findings


def _audit_exchange_variant(anchor) -> list:
    """Trace the MPP exchange-join shard_map shape (ISSUE 18): the
    shuffle-join chain — hash-partition both sides, all_to_all, local
    join, grouped agg phases — as ONE program (mpp/exchange_op.py
    `exchange_join_program`), walked through the same f64/host-callback/
    const jaxpr checks; iter_eqns recurses the shard_map body."""
    import jax

    from ..exec.dag import Aggregation, DAGRequest, Join
    from ..expr import AggDesc, col
    from ..mpp.exchange_op import exchange_join_program
    from ..parallel.mesh import region_mesh, stack_region_batches

    _ch, I = _int_chunk()
    dag = DAGRequest(
        (_scan(41, I),
         Join(build=(_scan(42, I),), probe_keys=(col(0, I),),
              build_keys=(col(0, I),), join_type="inner"),
         Aggregation(group_by=(col(1, I),),
                     aggs=(AggDesc("sum", (col(2, I),)),
                           AggDesc("count", ())))),
        output_offsets=(0, 1, 2))
    variant = "exchange_join/mesh"
    try:
        n_dev = len(jax.devices())
        mesh = region_mesh(n_dev)
        ch, _I = _int_chunk()
        stacked_p = stack_region_batches([ch] * n_dev, n_total=n_dev)
        stacked_b = stack_region_batches([ch] * n_dev, n_total=n_dev)
        fn = exchange_join_program(dag, mesh, group_capacity=_GROUP_CAPACITY)
        closed = jax.make_jaxpr(fn)(stacked_p, stacked_b)
    except Exception as exc:  # noqa: BLE001 — a trace failure IS a finding
        return [Finding(anchor[0], anchor[1], PASS,
                        f"program {variant!r} failed to trace: {exc}")]
    return audit_jaxpr(variant, closed, anchor)


def _audit_mesh_variant(name: str, dag, n_batches: int, anchor, caps=None) -> list:
    """Trace the MESH-tier shard_map variant (on-device psum of the
    batched partials) for every catalog shape the dispatch planner would
    route there, and walk its jaxpr through the same f64/host-callback/
    const checks — iter_eqns recurses the shard_map body like any other
    sub-jaxpr. Devices: whatever this process has (1 in the CLI, 8 under
    the test mesh) — the program specializes to the count either way."""
    import jax

    from ..distsql.planner import mesh_merge_kind
    from ..exec.builder import build_program

    kind = mesh_merge_kind(dag)
    if kind is None:
        return []
    variant = f"{name}/mesh-{kind}"
    entry_caps = _entry_caps(n_batches, caps)
    n_dev = min(len(jax.devices()), _VMAP_BATCH)
    lanes = -(-_VMAP_BATCH // n_dev) * n_dev
    try:
        cd = build_program(
            dag, entry_caps,
            group_capacity=_GROUP_CAPACITY,
            mesh_lanes=lanes, mesh_devices=n_dev, mesh_kind=kind)
        from ..chunk.device import to_stacked_device_batch

        ch, _I = _int_chunk()
        stacked = to_stacked_device_batch([ch] * lanes, entry_caps[0])
        aux = _batches(n_batches, False, caps)[1:]
        closed = jax.make_jaxpr(cd.fn)(stacked, *aux)
    except Exception as exc:  # noqa: BLE001 — a trace failure IS a finding
        return [Finding(anchor[0], anchor[1], PASS,
                        f"program {variant!r} failed to trace: {exc}")]
    return audit_jaxpr(variant, closed, anchor)


def _check_vmap_axis(name: str, single_avals, vmap_avals, anchor) -> list:
    rel, line = anchor
    if single_avals is None:
        return []
    if len(single_avals) != len(vmap_avals):
        return [Finding(rel, line, PASS,
                        f"program {name!r}: vmapped variant has {len(vmap_avals)} "
                        f"outputs vs {len(single_avals)} single — outputs dropped "
                        f"or added along the region axis")]
    out: list = []
    for i, (s, v) in enumerate(zip(single_avals, vmap_avals)):
        ss = tuple(getattr(s, "shape", ()))
        vs = tuple(getattr(v, "shape", ()))
        if vs != (_VMAP_BATCH,) + ss or str(getattr(s, "dtype", "")) != str(getattr(v, "dtype", "")):
            out.append(Finding(
                rel, line, PASS,
                f"program {name!r}: output #{i} rank/dtype inconsistent along "
                f"the region axis — single {ss}/{getattr(s, 'dtype', '?')} vs "
                f"vmapped {vs}/{getattr(v, 'dtype', '?')} (expected "
                f"{(_VMAP_BATCH,) + ss} with the same dtype)"))
    return out


# ----------------------------------------------------------- fixture mode

def _load_fixture_catalog(sf):
    spec = importlib.util.spec_from_file_location(
        f"_jaxaudit_fixture_{abs(hash(sf.path))}", sf.path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(spec.name, None)
    return getattr(mod, "JAX_AUDIT_CATALOG", [])


def audit_files(files) -> list:
    findings: list = []
    for sf in files:
        if "JAX_AUDIT_CATALOG" not in getattr(sf, "text", ""):
            continue  # never import modules that don't opt in — fixture
            # files for OTHER passes may have import side effects
        try:
            catalog = _load_fixture_catalog(sf)
        except Exception:  # noqa: BLE001 — non-catalog fixture files
            continue
        for entry in catalog:
            name = entry["name"]
            make = entry["make"]
            anchor = (sf.rel, entry.get("line", 1))
            try:
                fs, closed, _args = audit_stability(name, make, anchor)
            except Exception as exc:  # noqa: BLE001
                findings.append(Finding(
                    sf.rel, entry.get("line", 1), PASS,
                    f"program {name!r} failed to trace: {exc}"))
                continue
            findings.extend(fs)
            findings.extend(audit_jaxpr(name, closed, anchor))
    return findings


def run(files=None) -> list:
    """Vet-pass entry point: no `files` = the live builder catalog;
    explicit files = fixture catalogs (`JAX_AUDIT_CATALOG` modules)."""
    if files:
        return audit_files(files)
    return audit_live()
