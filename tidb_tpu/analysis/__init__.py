"""tidb-vet — the repo's static-analysis suite (ISSUE 7; ref: go vet /
Bazel nogo keeping the reference's 1.29M-LoC concurrent codebase honest;
`tools/failpoint_check.py` proved the pattern in PR 6 and this package
generalizes it).

Two families:

  * AST lint passes (stdlib `ast`, zero deps), each motivated by a bug a
    past PR actually paid for — see ANALYZERS.md for the catalog:
      jit-purity       module-level jax constants / config toggles
      lock-discipline  `# guarded_by:` attributes accessed off-lock
      error-taxonomy   bare RuntimeError/Exception in request paths
      metrics          registration/label consistency (shares promparse
                       with tools/scrape_check.py)
      wire-parity      encode_*/decode_* symmetry in codec/wire.py
      failpoints       armed names resolve to real injection sites
  * lockwatch (analysis/lockwatch.py) — the runtime lockset / lock-order
    detector the chaos and PD concurrency tests run under in tier-1.

Driver: `python tools/vet.py [--json]` — exit 0 clean, 1 on findings.
Suppress a finding with an inline `# vet: ignore[<pass>]` marker.
"""

from __future__ import annotations

from . import (
    error_taxonomy,
    failpoints,
    jit_purity,
    lock_discipline,
    metrics_lint,
    wire_parity,
)
from .common import REPO, Finding, SourceFile, filter_suppressed, load_files, py_files

# pass name -> (module, repo-relative scan roots); the scan roots encode
# each pass's blast radius (jit purity only matters where programs trace,
# error taxonomy where exceptions cross the session boundary, ...)
PASSES = {
    jit_purity.PASS: (jit_purity, ("tidb_tpu/ops", "tidb_tpu/exec",
                                   "tidb_tpu/expr", "tidb_tpu/parallel")),
    lock_discipline.PASS: (lock_discipline, ("tidb_tpu",)),
    error_taxonomy.PASS: (error_taxonomy, ("tidb_tpu/distsql", "tidb_tpu/store",
                                           "tidb_tpu/pd")),
    metrics_lint.PASS: (metrics_lint, ("tidb_tpu",)),
    wire_parity.PASS: (wire_parity, ("tidb_tpu/codec/wire.py",)),
    failpoints.PASS: (failpoints, ()),  # owns its own scoping
}


def run_pass(name: str, files=None) -> list:
    """Run one pass; `files` overrides the default scan roots (fixture
    testing). Suppression markers are honored either way."""
    mod, roots = PASSES[name]
    if files is None:
        files = load_files(py_files(*roots)) if roots else []
    findings = mod.run(files)
    by_rel = {sf.rel: sf for sf in files}
    return filter_suppressed(findings, by_rel)


def run_all() -> list:
    """Every pass over its default scope, findings sorted by location."""
    out: list = []
    for name in PASSES:
        out.extend(run_pass(name))
    return sorted(out, key=lambda f: (f.path, f.line, f.passname))
