"""tidb-vet — the repo's static-analysis suite (ISSUE 7 seeded the AST
lint passes; ISSUE 9 grew the interprocedural dataflow family and the
jaxpr program auditor; ref: go vet / Bazel nogo keeping the reference's
1.29M-LoC concurrent codebase honest).

Three families:

  * AST lint passes (stdlib `ast`, zero deps), each motivated by a bug a
    past PR actually paid for — see ANALYZERS.md for the catalog:
      jit-purity       module-level jax constants / config toggles
      lock-discipline  `# guarded_by:` attributes accessed off-lock
      metrics          registration/label consistency (shares promparse
                       with tools/scrape_check.py)
      wire-parity      encode_*/decode_* symmetry in codec/wire.py
      failpoints       armed names resolve to real injection sites
      suppressions     stale `# vet: ignore[...]` markers (audited from
                       the full-suite run)
  * interprocedural dataflow passes (analysis/dataflow.py): an
    AST-derived project call graph + forward fact propagation —
      dataflow-snapshot      MVCC reads on the request path flow start_ts
      dataflow-backoff       retry loops consult a Backoffer budget,
                             request-path sleeps are sliced/clamped
      dataflow-error-escape  typed errors map to SQLError codes before
                             the session boundary (supersedes PR-7's
                             lexical error-taxonomy)
    plus the jaxpr program auditor (analysis/jaxaudit.py, pass
    `jax-audit`): the exec builder's catalog traced to closed jaxprs and
    walked for f64 leaks, host callbacks, vmap axis drift and
    closure-captured scalars.
  * lockwatch (analysis/lockwatch.py) — the runtime lockset / lock-order
    detector the chaos, PD and replication concurrency tests run under
    in tier-1.

Driver: `python tools/vet.py [--json]` — exit 0 clean, 1 on findings.
Results cache per file revision in `.vet_cache.json` (analysis/
vetcache.py); suppress a finding with an inline `# vet: ignore[<pass>]`
marker (the `suppressions` pass flags markers that rot).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from . import (
    dataflow,
    failpoints,
    guards,  # noqa: F401 — re-exported for lockwatch/tests
    jaxaudit,
    jit_purity,
    lock_discipline,
    metrics_lint,
    promparse,
    suppress_audit,
    wire_parity,
)
from .common import REPO, Finding, SourceFile, filter_suppressed, load_files, py_files
from .vetcache import VetCache


@dataclass
class PassSpec:
    """One analyzer: how to run it, what it scans, how it caches.

    kind: "file"   — findings are a pure function of ONE file (cache per
                     (pass, file revision), runs parallelize per file)
          "corpus" — findings need the whole scope at once (cache per
                     (pass, corpus digest))
          "plain"  — self-scoped, uncached (failpoints: its inputs span
                     tests//tools//bench.py which aren't loaded here)
    """

    run: object  # callable(files) -> [Finding]
    roots: tuple
    kind: str
    mods: tuple = field(default_factory=tuple)  # implementation modules (cache key)
    salt: str = ""  # extra cache-key ingredient (e.g. jax version)
    live_files: bool = True  # live run receives the scope files; False =
    # the pass owns its live inputs (jax-audit traces the builder, and an
    # explicit file list means fixture mode) — roots then only scope the
    # cache digest


def _jax_salt() -> str:
    try:
        import jax

        return f"jax-{jax.__version__}"
    except Exception:  # noqa: BLE001
        return "jax-?"


# pass name -> spec; the scan roots encode each pass's blast radius (jit
# purity only matters where programs trace, wire parity at the codec
# seam, the dataflow passes across the whole package)
PASSES: dict[str, PassSpec] = {
    jit_purity.PASS: PassSpec(
        jit_purity.run,
        ("tidb_tpu/ops", "tidb_tpu/exec", "tidb_tpu/expr", "tidb_tpu/parallel"),
        "file", (jit_purity,)),
    lock_discipline.PASS: PassSpec(
        lock_discipline.run, ("tidb_tpu",), "file", (lock_discipline, guards)),
    metrics_lint.PASS: PassSpec(
        metrics_lint.run, ("tidb_tpu",), "corpus", (metrics_lint, promparse)),
    wire_parity.PASS: PassSpec(
        wire_parity.run, ("tidb_tpu/codec/wire.py",), "corpus", (wire_parity,)),
    failpoints.PASS: PassSpec(failpoints.run, (), "plain", (failpoints,)),
    dataflow.PASS_SNAPSHOT: PassSpec(
        dataflow.run_snapshot, ("tidb_tpu",), "corpus", (dataflow,)),
    dataflow.PASS_BACKOFF: PassSpec(
        dataflow.run_backoff, ("tidb_tpu",), "corpus", (dataflow,)),
    dataflow.PASS_ESCAPE: PassSpec(
        dataflow.run_escape, ("tidb_tpu",), "corpus", (dataflow,)),
    jaxaudit.PASS: PassSpec(
        jaxaudit.run, ("tidb_tpu",), "corpus", (jaxaudit,), salt=_jax_salt(),
        live_files=False),
}

# the suppressions auditor is driver-level: it needs every OTHER pass's
# pre-suppression findings, so it runs from run_all(), not standalone
SUPPRESSIONS = suppress_audit.PASS
ALL_PASS_NAMES = tuple(PASSES) + (SUPPRESSIONS,)


def _in_scope(sf: SourceFile, roots: tuple) -> bool:
    rel = sf.rel.replace(os.sep, "/")
    for r in roots:
        if rel == r or rel.startswith(r.rstrip("/") + "/"):
            return True
    return False


_POOL_WORKERS = min(8, (os.cpu_count() or 2))


def _load_tree(roots=("tidb_tpu",)) -> list[SourceFile]:
    """Parse the scan universe ONCE, in parallel — PR 7 re-loaded it per
    pass, which is where most of the old wall-clock went."""
    paths = py_files(*roots)
    with ThreadPoolExecutor(max_workers=_POOL_WORKERS) as pool:
        return list(pool.map(SourceFile.load, paths))


def _run_file_pass(name: str, spec: PassSpec, scope, cache: VetCache) -> list:
    psha = cache.pass_sha(*spec.mods)
    out: list = []
    misses: list = []
    for sf in scope:
        key = VetCache.file_key(name, psha, sf)
        hit = cache.get(key)
        if hit is None:
            misses.append((key, sf))
        else:
            out.extend(hit)
    if misses:
        with ThreadPoolExecutor(max_workers=_POOL_WORKERS) as pool:
            results = list(pool.map(lambda m: spec.run([m[1]]), misses))
        for (key, _sf), fnds in zip(misses, results):
            cache.put(key, fnds)
            out.extend(fnds)
    return out


def _run_corpus_pass(name: str, spec: PassSpec, scope, cache: VetCache) -> list:
    key = VetCache.corpus_key(name, cache.pass_sha(*spec.mods), scope, spec.salt)
    hit = cache.get(key)
    if hit is not None:
        return hit
    fnds = spec.run(scope) if (spec.roots and spec.live_files) else spec.run(None)
    cache.put(key, fnds)
    return fnds


def _run_live(name: str, spec: PassSpec, tree, cache: VetCache) -> list:
    """One pass over the live tree (pre-suppression findings)."""
    scope = [sf for sf in tree if _in_scope(sf, spec.roots)] if spec.roots else []
    if spec.kind == "file":
        return _run_file_pass(name, spec, scope, cache)
    if spec.kind == "corpus":
        return _run_corpus_pass(name, spec, scope, cache)
    return spec.run(None)


def run_pass(name: str, files=None) -> list:
    """Run one pass; `files` overrides the default scan roots (fixture
    testing). Suppression markers are honored either way."""
    if name == SUPPRESSIONS:
        raise ValueError(
            "the suppressions audit needs every other pass's verdict — "
            "it only runs from run_all() (or the vet CLI without --only)")
    if files is not None:
        findings = PASSES[name].run(files)
        return filter_suppressed(findings, {sf.rel: sf for sf in files})
    return run_only([name])


def run_only(names, cache: VetCache | None = None) -> list:
    """A subset of passes over the live tree — ONE shared parse and the
    same per-revision cache as run_all (the `--only` inner loop while
    fixing one pass's findings should not pay a cold run each time).
    The stale-suppression audit needs every pass's verdict, so it only
    rides full runs."""
    if cache is None:
        cache = VetCache()
    tree = _load_tree(("tidb_tpu",))
    by_rel = {sf.rel: sf for sf in tree}
    out: list = []
    for name in names:
        out.extend(filter_suppressed(_run_live(name, PASSES[name], tree, cache), by_rel))
    cache.save()
    return sorted(out, key=lambda f: (f.path, f.line, f.passname))


def run_all(cache: VetCache | None = None) -> list:
    """Every pass over its default scope — shared parse, per-revision
    cache, suppression filtering with marker-usage tracking, and the
    stale-suppression audit over the result. Findings sorted by
    location."""
    if cache is None:
        cache = VetCache()
    tree = _load_tree(("tidb_tpu",))
    by_rel = {sf.rel: sf for sf in tree}
    used_markers: set = set()
    out: list = []
    for name, spec in PASSES.items():
        fnds = _run_live(name, spec, tree, cache)
        out.extend(filter_suppressed(fnds, by_rel, used_markers))
    out.extend(suppress_audit.audit(
        tree, used_markers, ran_passes=set(PASSES), known_passes=set(ALL_PASS_NAMES)))
    cache.save()
    return sorted(out, key=lambda f: (f.path, f.line, f.passname))
