"""Per-file / per-corpus finding cache for the vet driver (ISSUE 9
satellite: the suite re-runs constantly — tier-1 runs it in-process AND
as subprocess CLI contract tests — and the AST passes are pure functions
of their input file revisions, so results cache by content).

Keys are self-invalidating: every key embeds the analyzed files'
(path, mtime, content sha) AND the sha of the pass's own implementation
modules — editing either the tree or an analyzer misses cleanly. Values
are PRE-suppression findings (suppression markers are re-applied on
every run so the stale-suppression audit always sees live data).

The cache file lives at `<repo>/.vet_cache.json` (gitignored;
`TIDB_TPU_VET_CACHE` overrides the path, an empty value disables).
Writes are atomic (tmp + rename) and best-effort — a corrupt or
unwritable cache degrades to a cold run, never a failure.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile

from .common import REPO, Finding

_DEFAULT_PATH = os.path.join(REPO, ".vet_cache.json")
_MAX_ENTRIES = 4000
_VERSION = 1


def _module_sha(mod) -> str:
    f = getattr(mod, "__file__", None)
    if not f:
        return "?"
    try:
        return hashlib.sha256(open(f, "rb").read()).hexdigest()[:16]
    except OSError:
        return "?"


class VetCache:
    def __init__(self, path: str | None = None):
        if path is None:
            path = os.environ.get("TIDB_TPU_VET_CACHE", _DEFAULT_PATH)
        self.path = path or None  # empty env value disables
        self._data: dict = {}
        self._dirty = False
        self._mod_shas: dict = {}
        if self.path:
            try:
                raw = json.load(open(self.path, encoding="utf-8"))
                if raw.get("version") == _VERSION:
                    self._data = raw.get("entries", {})
            except (OSError, ValueError):
                self._data = {}

    # -- keys ---------------------------------------------------------------
    def pass_sha(self, *mods) -> str:
        parts = []
        for m in mods:
            k = getattr(m, "__name__", str(m))
            if k not in self._mod_shas:
                self._mod_shas[k] = _module_sha(m)
            parts.append(self._mod_shas[k])
        return "+".join(parts)

    @staticmethod
    def file_key(passname: str, pass_sha: str, sf) -> str:
        return f"{passname}|{pass_sha}|{sf.rel}|{sf.mtime}|{sf.sha}"

    @staticmethod
    def corpus_key(passname: str, pass_sha: str, files, salt: str = "") -> str:
        h = hashlib.sha256()
        for sf in sorted(files, key=lambda s: s.rel):
            h.update(f"{sf.rel}:{sf.mtime}:{sf.sha}\n".encode())
        h.update(salt.encode())
        return f"{passname}|{pass_sha}|corpus|{h.hexdigest()}"

    # -- access -------------------------------------------------------------
    def get(self, key: str) -> list | None:
        ent = self._data.get(key)
        if ent is None:
            return None
        try:
            return [Finding(d["path"], d["line"], d["pass"], d["message"])
                    for d in ent]
        except (KeyError, TypeError):
            return None

    def put(self, key: str, findings: list) -> None:
        self._data[key] = [f.to_dict() for f in findings]
        self._dirty = True

    def save(self) -> None:
        if not (self.path and self._dirty):
            return
        entries = self._data
        if len(entries) > _MAX_ENTRIES:
            # drop the oldest insertions (dict order); newest stay
            entries = dict(list(entries.items())[-_MAX_ENTRIES:])
        try:
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(self.path) or ".", suffix=".vetcache")
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump({"version": _VERSION, "entries": entries}, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # best-effort: cold runs are always correct
