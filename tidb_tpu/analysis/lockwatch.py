"""lockwatch — opt-in runtime lockset / lock-order detector (ref: Eraser
[Savage et al. 1997] lockset checking and the Go race detector's
happens-before instrumentation, scaled down to what a pure-Python harness
can observe; the static lock-discipline pass proves the LEXICAL property,
this proves the DYNAMIC one — `# requires:` annotations the static pass
must trust are actually checked here).

Three cooperating mechanisms, all enabled by `watching()`:

  * lock wrapping — while installed, `threading.Lock()`/`RLock()` calls
    made FROM repo code return `WatchedLock` proxies that maintain each
    thread's held-lock stack (re-entrant RLock acquisitions don't grow
    it). Locks created by stdlib frames (pool/queue internals) stay real.
  * lock-order graph — acquiring lock B while holding lock A records the
    edge A->B, aggregated by lock CREATION SITE so an ABBA inversion
    between different instances of the same two locks is still a cycle.
    `report()["cycles"]` lists every cycle: each is a potential deadlock
    even if this run happened not to interleave into it.
  * guarded-attribute checking (Eraser-lite) — classes whose attributes
    carry `# guarded_by: <lock>` annotations get checking descriptors
    installed: once an (object, attr) has been touched by a second thread
    it is SHARED, and every later access must hold the annotated guard
    lock; an access with the guard absent from the thread's lockset is a
    data-race report. Objects touched by one thread only are exempt (the
    Eraser virgin/exclusive states), which is what makes __init__ and
    single-threaded tests quiet.

Accounting is keyed by id(obj) (slotted classes aren't weakref-able);
state is scoped to one `watching()` block, so id reuse across watches
cannot alias.
"""

from __future__ import annotations

import os
import sys
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

from .common import REPO

_SHARED = -1

# ONE per-thread held-lock stack shared by every LockWatch: a WatchedLock
# outlives its watch (global metric children keep theirs across tests),
# and a later watch must still see it held — per-watch stacks would
# misreport those acquisitions as absent
_TLS = threading.local()


def _held_stack() -> list:
    h = getattr(_TLS, "held", None)
    if h is None:
        h = _TLS.held = []  # [ [lock, count], ... ] in acquisition order
    return h


@dataclass
class Violation:
    cls: str
    attr: str
    mode: str  # read | write
    guard: str
    where: str  # file:line of the access
    thread: str

    def render(self) -> str:
        return (f"{self.where}: {self.cls}.{self.attr} {self.mode} without "
                f"holding {self.guard} (thread {self.thread})")


class WatchedLock:
    """Proxy over a real Lock/RLock that maintains the per-thread held set
    and feeds the acquisition-order graph."""

    def __init__(self, real, kind: str, site: str, watch: "LockWatch"):
        self._real = real
        self.kind = kind  # "Lock" | "RLock"
        self.site = site  # creation file:line — the aggregation key
        self._watch = watch

    # -- lock protocol ------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._real.acquire(blocking, timeout)
        if ok:
            self._watch._acquired(self)
        return ok

    def release(self):
        self._watch._released(self)
        self._real.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._real.locked() if hasattr(self._real, "locked") else None

    def _is_owned(self):  # Condition compatibility
        return self._real._is_owned() if hasattr(self._real, "_is_owned") else None

    def __repr__(self):
        return f"<WatchedLock {self.kind} {self.site}>"


class _GuardedAttr:
    """Data descriptor standing in for one annotated attribute; delegates
    storage to the original slot descriptor (slotted classes) or the
    instance __dict__, checking the thread's lockset around each access."""

    def __init__(self, attr: str, lockname: str, orig, watch: "LockWatch"):
        self.attr = attr
        self.lockname = lockname
        self.orig = orig  # member_descriptor / previous class attr / None
        self.watch = watch

    def _check(self, obj, mode: str):
        self.watch._access(obj, self.attr, self.lockname, mode)

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self._check(obj, "read")
        if self.orig is not None:
            return self.orig.__get__(obj, objtype)
        try:
            return obj.__dict__[self.attr]
        except KeyError:
            raise AttributeError(self.attr) from None

    def __set__(self, obj, value):
        self._check(obj, "write")
        if self.orig is not None:
            self.orig.__set__(obj, value)
        else:
            obj.__dict__[self.attr] = value

    def __delete__(self, obj):
        self._check(obj, "write")
        if self.orig is not None:
            self.orig.__delete__(obj)
        else:
            del obj.__dict__[self.attr]


class LockWatch:
    """One watching session: installed factories, the order graph, the
    guard descriptors and every report they produced."""

    def __init__(self, repo: str = REPO):
        self.repo = repo
        self._mu = threading.Lock()  # real lock (created pre-install)
        self.edges: dict[tuple[str, str], str] = {}  # (src, dst) -> example
        self.violations: list[Violation] = []
        self._owners: dict[tuple[int, str], int] = {}  # (id(obj), attr) -> tid|_SHARED
        self._installed = False
        self._patched: list[tuple[type, str, object, bool]] = []
        self._orig_lock = None
        self._orig_rlock = None

    # -- per-thread held stack ---------------------------------------------
    def _held(self) -> list:
        return _held_stack()

    def _acquired(self, lock: WatchedLock):
        held = self._held()
        for ent in held:
            if ent[0] is lock:  # re-entrant RLock acquisition
                ent[1] += 1
                return
        if held:
            with self._mu:
                for ent in held:
                    src = ent[0].site
                    if src != lock.site:
                        self.edges.setdefault((src, lock.site), _caller())
        held.append([lock, 1])

    def _released(self, lock: WatchedLock):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                held[i][1] -= 1
                if held[i][1] <= 0:
                    del held[i]
                return

    def held_locks(self) -> list:
        return [ent[0] for ent in self._held()]

    # -- Eraser-lite guarded access ----------------------------------------
    def _access(self, obj, attr: str, lockname: str, mode: str):
        tid = threading.get_ident()
        key = (id(obj), attr)
        owner = self._owners.get(key)
        if owner is None:
            self._owners[key] = tid  # virgin -> exclusive
            return
        if owner == tid:
            return  # still exclusive to its first thread
        if owner != _SHARED:
            self._owners[key] = _SHARED  # second thread arrived
        lock = getattr(obj, lockname, None)
        if lock is None:
            mod = sys.modules.get(type(obj).__module__)
            lock = getattr(mod, lockname, None) if mod else None
        if not isinstance(lock, WatchedLock):
            return  # guard created outside the watch: cannot verify
        for ent in self._held():
            if ent[0] is lock:
                return
        with self._mu:
            self.violations.append(Violation(
                type(obj).__name__, attr, mode, lockname, _caller(2),
                threading.current_thread().name))

    # -- installation -------------------------------------------------------
    def install(self):
        assert not self._installed
        self._orig_lock, self._orig_rlock = threading.Lock, threading.RLock
        threading.Lock = self._factory(self._orig_lock, "Lock")
        threading.RLock = self._factory(self._orig_rlock, "RLock")
        self._installed = True
        return self

    def _factory(self, real_ctor, kind: str):
        repo = self.repo + os.sep
        watch = self

        def make(*a, **kw):
            real = real_ctor(*a, **kw)
            f = sys._getframe(1)
            fn = f.f_code.co_filename
            if fn.startswith(repo) and os.sep + "analysis" + os.sep not in fn:
                rel = os.path.relpath(fn, watch.repo)
                return WatchedLock(real, kind, f"{rel}:{f.f_lineno}", watch)
            return real

        return make

    def guard_class(self, cls: type, attrs: dict[str, str]):
        """Install checking descriptors for `attrs` ({attr: lockname})."""
        for attr, lockname in attrs.items():
            had = attr in cls.__dict__
            orig = cls.__dict__.get(attr)
            if isinstance(orig, _GuardedAttr):
                continue
            # only delegate to real descriptors (slots); plain class-level
            # defaults fall back to instance-dict storage
            deleg = orig if (orig is not None and hasattr(orig, "__set__")) else None
            setattr(cls, attr, _GuardedAttr(attr, lockname, deleg, self))
            self._patched.append((cls, attr, orig, had))

    def guard_tree(self, packages=("tidb_tpu",)):
        """Collect `# guarded_by:` annotations from the source tree and
        guard every annotated class that is already imported (unimported
        modules are imported on demand)."""
        import importlib

        from . import guards as _g
        from .common import load_files, py_files

        for sf in load_files(py_files(*packages, repo=self.repo)):
            if sf.tree is None:
                continue
            g = _g.collect(sf.tree, sf.lines)
            if not g.classes:
                continue
            mod_name = sf.rel[:-3].replace(os.sep, ".")
            if mod_name.endswith(".__init__"):
                mod_name = mod_name[: -len(".__init__")]
            try:
                mod = sys.modules.get(mod_name) or importlib.import_module(mod_name)
            except Exception:  # noqa: BLE001 — unimportable module: skip
                continue
            for cls_name, attrs in g.classes.items():
                cls = getattr(mod, cls_name, None)
                if isinstance(cls, type):
                    self.guard_class(cls, attrs)
        return self

    def uninstall(self):
        if self._installed:
            threading.Lock = self._orig_lock
            threading.RLock = self._orig_rlock
            self._installed = False
        for cls, attr, orig, had in reversed(self._patched):
            if had:
                setattr(cls, attr, orig)
            else:
                try:
                    delattr(cls, attr)
                except AttributeError:
                    pass
        self._patched.clear()

    # -- reporting ----------------------------------------------------------
    def cycles(self) -> list[list[str]]:
        """Every elementary cycle in the site-level acquisition-order
        graph (each is a potential deadlock ordering)."""
        adj: dict[str, set] = {}
        for src, dst in self.edges:
            adj.setdefault(src, set()).add(dst)
        out: list[list[str]] = []
        seen_cycles: set = set()

        def dfs(node, path, on_path):
            for nxt in sorted(adj.get(node, ())):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(cyc)
                    continue
                if (node, nxt) in visited_edges:
                    continue
                visited_edges.add((node, nxt))
                dfs(nxt, path + [nxt], on_path | {nxt})

        visited_edges: set = set()
        for start in sorted(adj):
            dfs(start, [start], {start})
        return out

    def report(self) -> dict:
        return {
            "edges": sorted(f"{a} -> {b}" for a, b in self.edges),
            "cycles": self.cycles(),
            "violations": [v.render() for v in self.violations],
        }


@contextmanager
def watching(guard_tree: bool = True, packages=("tidb_tpu",)):
    """Run a block under lockwatch; yields the LockWatch (read `.report()`
    before the block exits or keep the reference). Not re-entrant."""
    w = LockWatch()
    w.install()
    try:
        if guard_tree:
            w.guard_tree(packages)
        yield w
    finally:
        w.uninstall()


def _caller(extra: int = 0) -> str:
    """file:line of the first non-lockwatch frame."""
    f = sys._getframe(2 + extra)
    here = os.path.dirname(os.path.abspath(__file__))
    while f is not None and f.f_code.co_filename.startswith(here):
        f = f.f_back
    if f is None:
        return "?"
    try:
        rel = os.path.relpath(f.f_code.co_filename, REPO)
    except ValueError:
        rel = f.f_code.co_filename
    return f"{rel}:{f.f_lineno}"
