"""The `guarded_by` annotation convention — collection shared by the
static lock-discipline pass and the runtime lockwatch detector (ref:
Clang's thread-safety attributes: GUARDED_BY on fields, REQUIRES on
functions; here they are structured comments, the only metadata channel a
runtime-typed codebase has).

Convention:

  * `self.attr = ...  # guarded_by: _mu` on an attribute's defining
    assignment (normally in __init__) declares that every read/write of
    `self.attr` must happen while `self._mu` is held. The lock name may
    also be a module-level lock (`# guarded_by: _ALLOC_LOCK`).
  * `GLOBAL = {}  # guarded_by: _lock` at module level declares the same
    for a module global.
  * `def _helper(self):  # requires: _mu` on a def line declares that the
    function runs with `_mu` already held (RLock re-entry or private
    helpers only called under the lock) — its body counts as guarded.
    The static pass trusts this declaration; the runtime detector checks
    the real held set, so a wrong `requires` still surfaces under
    lockwatch.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

# the marker may trail other comment text (`# LRU ring; guarded_by: _mu`)
GUARDED = re.compile(r"#.*?\bguarded_by:\s*([A-Za-z_]\w*)")
REQUIRES = re.compile(r"#.*?\brequires:\s*([A-Za-z_]\w*)")
_SELF_ATTR = re.compile(r"self\.([A-Za-z_]\w*)\s*(?::[^=]+)?=")
_GLOBAL_ATTR = re.compile(r"^([A-Za-z_]\w*)\s*(?::[^=]+)?=")
# dataclass-style class field: `_next_handle: int = 1  # guarded_by: ...`
_FIELD_ATTR = re.compile(r"^\s+([A-Za-z_]\w*)\s*:[^=#]*=")


@dataclass
class ModuleGuards:
    """Annotations of one module. `classes` maps class name ->
    {attr: lockname}; `globals_` maps global name -> lockname;
    `requires` maps (class-or-'' , funcname) -> lockname."""

    classes: dict = field(default_factory=dict)
    globals_: dict = field(default_factory=dict)
    requires: dict = field(default_factory=dict)

    def any(self) -> bool:
        return bool(self.classes or self.globals_ or self.requires)


def _class_spans(tree: ast.AST) -> list[tuple[str, int, int]]:
    """(name, first_line, last_line) of every top-level-ish class."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out.append((node.name, node.lineno, node.end_lineno or node.lineno))
    return out


def collect(tree: ast.AST | None, lines: list[str]) -> ModuleGuards:
    """Scan a module's comments for guarded_by / requires annotations."""
    g = ModuleGuards()
    if tree is None:
        return g
    spans = _class_spans(tree)

    def owner_of(line_no: int) -> str | None:
        best = None
        for name, lo, hi in spans:
            if lo <= line_no <= hi and (best is None or lo > best[1]):
                best = (name, lo)
        return best[0] if best else None

    for ln, line in enumerate(lines, 1):
        m = GUARDED.search(line)
        if m:
            lock = m.group(1)
            cls = owner_of(ln)
            am = _SELF_ATTR.search(line)
            if cls is not None and am:
                g.classes.setdefault(cls, {})[am.group(1)] = lock
            elif cls is not None:
                fm = _FIELD_ATTR.match(line)
                if fm:
                    g.classes.setdefault(cls, {})[fm.group(1)] = lock
            else:
                gm = _GLOBAL_ATTR.match(line)
                if gm:
                    g.globals_[gm.group(1)] = lock
        r = REQUIRES.search(line)
        if r and re.search(r"^\s*def\s+(\w+)", line):
            fn = re.search(r"^\s*def\s+(\w+)", line).group(1)
            g.requires[(owner_of(ln) or "", fn)] = r.group(1)
    return g
