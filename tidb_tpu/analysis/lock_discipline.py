"""`lock-discipline` — statically verify every access to a
`# guarded_by: <lock>`-annotated attribute happens lexically inside
`with self.<lock>:` (or `with <lock>:` for module-level locks), in the
spirit of go vet's lostcancel/copylocks family and Clang GUARDED_BY
checking (ref: the PR-4 cop-cache TOCTOU and the PR-6 PD timer thread —
both were exactly "shared attribute touched off-lock").

Rules:
  * `__init__` bodies are exempt (object construction precedes sharing —
    the Eraser initialization exemption).
  * a `# requires: <lock>` def-line annotation treats the whole function
    body as holding the lock (validated dynamically by lockwatch).
  * module-level definition lines of annotated globals are exempt.
"""

from __future__ import annotations

import ast

from . import guards as _guards
from .common import Finding, SourceFile

PASS = "lock-discipline"


def _with_locks(node: ast.With) -> set:
    out = set()
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Attribute) and isinstance(ctx.value, ast.Name) \
                and ctx.value.id == "self":
            out.add(ctx.attr)
        elif isinstance(ctx, ast.Name):
            out.add(ctx.id)
    return out


class _FuncChecker(ast.NodeVisitor):
    """Walk one function body tracking the lexically-held lock set."""

    def __init__(self, sf: SourceFile, attrs: dict, globals_: dict,
                 held: set, findings: list):
        self.sf = sf
        self.attrs = attrs  # attr -> lockname (self.<attr> accesses)
        self.globals_ = globals_  # name -> lockname (module globals)
        self.held = set(held)
        self.findings = findings

    def visit_With(self, node: ast.With):
        added = _with_locks(node) - self.held
        self.held |= added
        for stmt in node.body:
            self.visit(stmt)
        self.held -= added

    def visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            lock = self.attrs.get(node.attr)
            if lock is not None and lock not in self.held:
                verb = "written" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
                self.findings.append(Finding(
                    self.sf.rel, node.lineno, PASS,
                    f"self.{node.attr} (guarded_by {lock}) {verb} outside `with self.{lock}`"))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        lock = self.globals_.get(node.id)
        if lock is not None and lock not in self.held:
            verb = "written" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
            self.findings.append(Finding(
                self.sf.rel, node.lineno, PASS,
                f"module global {node.id} (guarded_by {lock}) {verb} outside `with {lock}`"))
        self.generic_visit(node)


def _check_function(sf: SourceFile, fn: ast.FunctionDef, attrs: dict,
                    globals_: dict, base_held: set, findings: list):
    checker = _FuncChecker(sf, attrs, globals_, base_held, findings)
    for stmt in fn.body:
        checker.visit(stmt)


def run(files) -> list:
    findings: list = []
    for sf in files:
        if sf.tree is None:
            continue
        g = _guards.collect(sf.tree, sf.lines)
        if not g.any():
            continue
        fns = [n for n in ast.walk(sf.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        spans = [(f.lineno, f.end_lineno or f.lineno) for f in fns]
        for node in fns:
            # nested defs are visited as part of their enclosing function
            # (they inherit its lexical lock set — closures run inline)
            if any(lo < node.lineno and (node.end_lineno or node.lineno) <= hi
                   for lo, hi in spans if (lo, hi) != (node.lineno, node.end_lineno or node.lineno)):
                continue
            cls = _owner_class(sf.tree, node)
            attrs = g.classes.get(cls, {}) if cls else {}
            # methods may also touch annotated module globals
            if not attrs and not g.globals_:
                continue
            if cls and node.name in ("__init__", "__post_init__"):
                continue  # construction precedes sharing
            held = set()
            req = g.requires.get((cls or "", node.name))
            if req:
                held.add(req)
            _check_function(sf, node, attrs, g.globals_, held, findings)
    return findings


def _owner_class(tree: ast.AST, fn: ast.FunctionDef) -> str | None:
    """Name of the class whose body directly contains `fn` (None for
    module-level functions; nested defs inherit their method's class)."""
    best = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            lo, hi = node.lineno, node.end_lineno or node.lineno
            if lo <= fn.lineno <= hi and (best is None or lo > best[1]):
                best = (node.name, lo)
    return best[0] if best else None
