"""`wire-parity` — encode/decode symmetry over codec/wire.py (ref: the
protobuf contract the reference gets for free from .proto codegen; a
hand-rolled tagged binary format has no generator, so symmetry is a lint
invariant instead).

For every `encode_X`/`w_X` in the wire module there must be a matching
`decode_X`/`r_X`, and the pair must cover the SAME fields:

  * the set of primitive writer ops used (`w.u8/i32/i64/u64/f64/blob/s/
    bool_`) equals the set of primitive reader ops (`r.<same>`), so a
    field written in one width can never be read back in another — and a
    field written but never read (or vice versa) shifts the stream for
    everything after it;
  * helper calls pair up: `w_foo`/`encode_foo` on the write side must be
    mirrored by `r_foo`/`decode_foo` on the read side.

Sets (not call counts) are compared: loops and per-kind branches
legitimately differ in call-site counts (e.g. one shared `w.f64` for two
float kinds decodes through two `r.f64` branches).
"""

from __future__ import annotations

import ast

from .common import Finding

PASS = "wire-parity"

_PRIMS = {"u8", "i32", "i64", "u64", "f64", "blob", "s", "bool_"}


def _is_codec_fn(name: str) -> str | None:
    """-> role key for pairing: ('encode'|'decode'|'w'|'r', stem)."""
    for prefix, role in (("encode_", "encode"), ("decode_", "decode"),
                         ("w_", "w"), ("r_", "r")):
        if name.startswith(prefix):
            return f"{role}:{name[len(prefix):]}"
    return None


_MIRROR = {"encode": "decode", "decode": "encode", "w": "r", "r": "w"}


def _profile(fn: ast.FunctionDef) -> tuple[set, set]:
    """(primitive ops, helper stems) used by one codec function."""
    prims: set = set()
    helpers: set = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _PRIMS and isinstance(f.value, ast.Name):
            prims.add(f.attr)
        elif isinstance(f, ast.Name):
            key = _is_codec_fn(f.id)
            if key is not None:
                role, stem = key.split(":", 1)
                helpers.add((role, stem))
    return prims, helpers


def run(files) -> list:
    findings: list = []
    for sf in files:
        if sf.tree is None or not sf.rel.endswith("wire.py"):
            continue
        fns = {n.name: n for n in sf.tree.body if isinstance(n, ast.FunctionDef)}
        roles: dict[str, ast.FunctionDef] = {}
        for name, fn in fns.items():
            key = _is_codec_fn(name)
            if key is not None:
                roles[key] = fn
        for key, fn in sorted(roles.items()):
            role, stem = key.split(":", 1)
            if role in ("decode", "r"):
                continue  # pairs are reported from the write side
            mirror = f"{_MIRROR[role]}:{stem}"
            partner = roles.get(mirror)
            if partner is None:
                findings.append(Finding(
                    sf.rel, fn.lineno, PASS,
                    f"{fn.name} has no matching "
                    f"{_MIRROR[role]}_{stem} — every encoder needs a decoder "
                    f"(round-trip parity)"))
                continue
            wp, wh = _profile(fn)
            rp, rh = _profile(partner)
            if wp != rp:
                only_w = sorted(wp - rp)
                only_r = sorted(rp - wp)
                detail = []
                if only_w:
                    detail.append(f"written but never read: {only_w}")
                if only_r:
                    detail.append(f"read but never written: {only_r}")
                findings.append(Finding(
                    sf.rel, fn.lineno, PASS,
                    f"{fn.name}/{partner.name} field-kind mismatch — "
                    + "; ".join(detail)))
            wh_m = {(_MIRROR[r], s) for r, s in wh}
            if wh_m != rh:
                only_w = sorted(s for r, s in wh if (_MIRROR[r], s) not in rh)
                only_r = sorted(s for r, s in rh if (r, s) not in wh_m)
                detail = []
                if only_w:
                    detail.append(f"encoded sub-structures with no decode: {only_w}")
                if only_r:
                    detail.append(f"decoded sub-structures never encoded: {only_r}")
                findings.append(Finding(
                    sf.rel, fn.lineno, PASS,
                    f"{fn.name}/{partner.name} sub-structure mismatch — "
                    + "; ".join(detail)))
    return findings
