"""`metrics` — registration/use consistency for the Prometheus registry
(ref: client_golang panicking on duplicate registration and label-arity
mismatch at runtime; here both become lint findings before any scrape).

Checks:
  * every literal metric name is registered at exactly ONE call site
  * registered names satisfy the exposition grammar (promparse — the SAME
    parser tools/scrape_check.py validates dumps with) and the naming
    conventions: counters end `_total`, gauges don't, histograms carry a
    unit suffix (`_seconds`/`_bytes`)
  * declared label names are valid
  * every `metrics.<CONST>` use site resolves to a registered instrument;
    vec instruments are always addressed through `.labels(...)` with the
    registration's exact arity (positional) or exact names (keyword), and
    plain instruments never are
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from . import promparse
from .common import Finding

PASS = "metrics"

_KINDS = {
    "counter": "counter", "gauge": "gauge", "histogram": "histogram",
    "counter_vec": "counter", "gauge_vec": "gauge", "histogram_vec": "histogram",
}
_VEC_KINDS = {"counter_vec", "gauge_vec", "histogram_vec"}
_CHILD_METHODS = {"inc", "dec", "set", "observe"}


@dataclass
class Registration:
    name: str
    method: str  # counter / counter_vec / ...
    labelnames: tuple | None
    const: str | None
    rel: str
    line: int


def _literal_str(node) -> str | None:
    return node.value if isinstance(node, ast.Constant) and isinstance(node.value, str) else None


def _labelnames(call: ast.Call) -> tuple | None:
    for kw in call.keywords:
        if kw.arg == "labelnames":
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                vals = [_literal_str(e) for e in kw.value.elts]
                if all(v is not None for v in vals):
                    return tuple(vals)
            return None  # non-literal: cannot check
    # positional third arg
    if len(call.args) >= 3 and isinstance(call.args[2], (ast.Tuple, ast.List)):
        vals = [_literal_str(e) for e in call.args[2].elts]
        if all(v is not None for v in vals):
            return tuple(vals)
        return None  # non-literal: cannot check
    return ()  # a vec registered without labelnames


def _collect_registrations(files) -> tuple[list, list]:
    regs: list[Registration] = []
    findings: list = []
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            call = None
            const = None
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                call = node.value
                if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                    const = node.targets[0].id
            elif isinstance(node, ast.Call):
                call = node
            if call is None or not isinstance(call.func, ast.Attribute):
                continue
            method = call.func.attr
            if method not in _KINDS or not call.args:
                continue
            name = _literal_str(call.args[0])
            if name is None:
                continue
            labelnames = _labelnames(call) if method in _VEC_KINDS else None
            regs.append(Registration(name, method, labelnames, const, sf.rel, call.lineno))
    # de-dup Assign/Call double-walk hits (the Call inside an Assign is
    # walked twice); keep one per (file, line, name)
    seen = set()
    uniq = []
    for r in regs:
        key = (r.rel, r.line, r.name)
        if key in seen:
            continue
        seen.add(key)
        uniq.append(r)
    return uniq, findings


def _check_registrations(regs) -> list:
    findings: list = []
    by_name: dict[str, list] = {}
    for r in regs:
        by_name.setdefault(r.name, []).append(r)
    for name, rs in sorted(by_name.items()):
        if len(rs) > 1:
            sites = ", ".join(f"{r.rel}:{r.line}" for r in rs[1:])
            findings.append(Finding(rs[0].rel, rs[0].line, PASS,
                                    f"metric {name!r} registered more than once (also at {sites}) — "
                                    f"one registration site per family"))
        r = rs[0]
        if not promparse.valid_metric_name(name):
            findings.append(Finding(r.rel, r.line, PASS,
                                    f"invalid metric name {name!r}"))
        kind = _KINDS[r.method]
        if kind == "counter" and not name.endswith(promparse.COUNTER_SUFFIX):
            findings.append(Finding(r.rel, r.line, PASS,
                                    f"counter {name!r} must end `_total` (prometheus naming)"))
        if kind != "counter" and name.endswith(promparse.COUNTER_SUFFIX):
            findings.append(Finding(r.rel, r.line, PASS,
                                    f"{kind} {name!r} must not claim the counter suffix `_total`"))
        if kind == "histogram" and not name.endswith(("_seconds", "_bytes")):
            findings.append(Finding(r.rel, r.line, PASS,
                                    f"histogram {name!r} should carry a base-unit suffix (_seconds/_bytes)"))
        for ln in (r.labelnames or ()):
            if not promparse.valid_label_name(ln):
                findings.append(Finding(r.rel, r.line, PASS,
                                        f"invalid label name {ln!r} on {name!r}"))
    return findings


def _metrics_aliases(tree: ast.AST) -> set:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "metrics":
                    out.add(a.asname or "metrics")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith(".metrics"):
                    out.add(a.asname or a.name.split(".")[0])
    return out


def _check_uses(files, regs) -> list:
    by_const = {r.const: r for r in regs if r.const}
    findings: list = []
    for sf in files:
        if sf.tree is None:
            continue
        aliases = _metrics_aliases(sf.tree)
        if not aliases:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            base = node.func.value
            # metrics.CONST.labels(...) / metrics.CONST.inc(...)
            if not (isinstance(base, ast.Attribute) and isinstance(base.value, ast.Name)
                    and base.value.id in aliases):
                continue
            const = base.attr
            if const == "REGISTRY" or not const.isupper():
                continue
            reg = by_const.get(const)
            if reg is None:
                if meth in _CHILD_METHODS | {"labels"}:
                    findings.append(Finding(sf.rel, node.lineno, PASS,
                                            f"metrics.{const} is not a registered instrument"))
                continue
            is_vec = reg.method in _VEC_KINDS
            if meth == "labels":
                if not is_vec:
                    findings.append(Finding(sf.rel, node.lineno, PASS,
                                            f"{reg.name!r} is a plain {_KINDS[reg.method]} — it has no .labels()"))
                elif reg.labelnames is not None:
                    if node.keywords:
                        names = tuple(kw.arg for kw in node.keywords)
                        if set(names) != set(reg.labelnames) or node.args:
                            findings.append(Finding(
                                sf.rel, node.lineno, PASS,
                                f"{reg.name!r} label set mismatch: registered {reg.labelnames}, "
                                f"called with {names}"))
                    elif len(node.args) != len(reg.labelnames):
                        findings.append(Finding(
                            sf.rel, node.lineno, PASS,
                            f"{reg.name!r} takes {len(reg.labelnames)} label value(s) "
                            f"{reg.labelnames}, got {len(node.args)}"))
            elif meth in _CHILD_METHODS and is_vec:
                findings.append(Finding(
                    sf.rel, node.lineno, PASS,
                    f"{reg.name!r} is a labeled family — address a child via "
                    f".labels({', '.join(reg.labelnames or ())}) before .{meth}()"))
    return findings


def run(files) -> list:
    regs, findings = _collect_registrations(files)
    findings += _check_registrations(regs)
    findings += _check_uses(files, regs)
    return findings
