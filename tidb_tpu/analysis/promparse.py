"""The ONE Prometheus metric-name / label grammar for this repo.

Both consumers of the exposition contract parse with this module:
`tools/scrape_check.py` (validates `Registry.dump()` output at scrape
time) and the `metrics` vet pass (validates registrations and `.labels()`
call sites at lint time). Before this module each kept its own regexes —
exactly the drift a consistency checker exists to prevent.

Grammar (the text-exposition v0.0.4 subset):
  metric name  [a-zA-Z_:][a-zA-Z0-9_:]*
  label name   [a-zA-Z_][a-zA-Z0-9_]*
  label set    k="v" pairs, comma separated, backslash escapes in values
"""

from __future__ import annotations

import re

METRIC_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
EXPOSITION_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}

# naming conventions the registry adheres to (prometheus.io/docs/practices/
# naming): cumulative counters end `_total`; base units are suffixed
# (`_seconds`, `_bytes`); gauges never claim `_total`.
COUNTER_SUFFIX = "_total"
UNIT_SUFFIXES = ("_seconds", "_bytes", "_total", "_count")


def valid_metric_name(name: str) -> bool:
    return bool(METRIC_NAME.match(name))


def valid_label_name(name: str) -> bool:
    return bool(LABEL_NAME.match(name))


def parse_labels(s: str, errs: list, ln: int) -> dict:
    """`k="v",k2="v2"` -> dict; appends errors instead of raising."""
    out: dict = {}
    i = 0
    while i < len(s):
        m = re.match(r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"', s[i:])
        if not m:
            errs.append(f"line {ln}: bad label syntax at {s[i:]!r}")
            return out
        key = m.group(1)
        i += m.end()
        buf = []
        while i < len(s):
            c = s[i]
            if c == "\\":
                if i + 1 >= len(s):
                    errs.append(f"line {ln}: dangling escape in label value")
                    return out
                nxt = s[i + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                i += 2
                continue
            if c == '"':
                i += 1
                break
            buf.append(c)
            i += 1
        else:
            errs.append(f"line {ln}: unterminated label value for {key!r}")
            return out
        out[key] = "".join(buf)
        if i < len(s) and s[i] == ",":
            i += 1
    return out
