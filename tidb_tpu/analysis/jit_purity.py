"""`jit-purity` — no module-level JAX array constants or global-config
mutation in the jit-sensitive packages (ops/, exec/, expr/, parallel/).

The PR-2 fixup chased exactly this class: a module whose top level runs
`X = jnp.int64(...)` gets its constant created whenever the module is
FIRST imported — and if that first import happens inside a jit trace, the
"constant" captures the trace (a leaked tracer) or the ambient x64 mode,
poisoning every later program built from it. Likewise a module-level
`enable_x64(...)` / `jax.config.update(...)` call flips global state for
whoever happens to import second.

Flagged at module level only — inside a function, jnp expressions trace
fresh per program, which is the correct place for them.
"""

from __future__ import annotations

import ast

from .common import Finding

PASS = "jit-purity"

# attribute roots whose module-level use constructs device values
_JAX_ROOTS = {"jnp", "jax"}
_IMPURE_CALLS = {"enable_x64", "update", "disable_x64"}


def _jax_aliases(tree: ast.AST) -> set:
    """Local names bound to jax / jax.numpy by imports."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("jax", "jax.numpy"):
                    names.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax" and any(a.name == "numpy" for a in node.names):
                for a in node.names:
                    if a.name == "numpy":
                        names.add(a.asname or "numpy")
    return names or set(_JAX_ROOTS)


def _rooted_in_jax(node: ast.AST, aliases: set) -> ast.AST | None:
    """First CALL rooted at a jax alias (jnp.int64(...), jax.numpy.array(...),
    jnp.zeros(...).reshape(...)…), else None. Bare attribute references
    (`jnp.bitwise_and` in a dispatch table) construct no device value and
    are fine at module level."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        cur = sub
        while isinstance(cur, (ast.Attribute, ast.Call, ast.Subscript)):
            cur = cur.func if isinstance(cur, ast.Call) else cur.value
        if isinstance(cur, ast.Name) and cur.id in aliases:
            return sub
    return None


def run(files) -> list:
    findings: list = []
    for sf in files:
        if sf.tree is None:
            continue
        aliases = _jax_aliases(sf.tree)
        for node in sf.tree.body:  # MODULE level only
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = node.value
                if value is None:
                    continue
                hit = _rooted_in_jax(value, aliases)
                if hit is not None:
                    tgt = _target_name(node)
                    findings.append(Finding(
                        sf.rel, node.lineno, PASS,
                        f"module-level jax value bound to {tgt}: created at import "
                        f"time, it captures whatever trace/x64 mode is ambient when "
                        f"this module first loads (the PR-2 tracer-leak class) — "
                        f"build it inside the function, or use a numpy/python constant"))
            elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                fn = node.value.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else "")
                if name in _IMPURE_CALLS:
                    findings.append(Finding(
                        sf.rel, node.lineno, PASS,
                        f"module-level call to {name}() mutates global jax config at "
                        f"import time — import order becomes semantics; gate it in a "
                        f"function or context manager"))
    return findings


def _target_name(node) -> str:
    t = node.targets[0] if isinstance(node, ast.Assign) else node.target
    try:
        return ast.unparse(t)
    except Exception:  # noqa: BLE001
        return "<target>"
