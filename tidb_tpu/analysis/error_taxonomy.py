"""`error-taxonomy` — request paths raise TYPED errors, never bare
RuntimeError/Exception (ref: the reference's errno/terror discipline:
every region/cop failure maps to a typed error with a MySQL code; PR 6
replaced the seed's bare RuntimeErrors in dispatch with
RegionUnavailableError/CopInternalError and this pass keeps it that way).

Scope: tidb_tpu/distsql/, tidb_tpu/store/, tidb_tpu/pd/ — the request
paths whose exceptions cross the session boundary and must map onto
MySQL error codes. `raise RuntimeError(...)` / `raise Exception(...)`
there silently degrades to error 1105 with no classification, no backoff
budget, and no breaker accounting.
"""

from __future__ import annotations

import ast

from .common import Finding

PASS = "error-taxonomy"

_BARE = {"RuntimeError", "Exception"}


def run(files) -> list:
    findings: list = []
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in _BARE:
                findings.append(Finding(
                    sf.rel, node.lineno, PASS,
                    f"bare `raise {name}` in a request path — use a typed error "
                    f"from store/errors.py (or a subsystem exception with a MySQL "
                    f"code mapping) so dispatch can classify, back off and account it"))
    return findings
