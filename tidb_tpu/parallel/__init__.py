from .mesh import region_mesh, stack_region_batches, run_sharded_partial_agg
from .exchange import hash_partition_ids, exchange_group_aggregate
from .grouped import run_sharded_grouped_agg

__all__ = [
    "region_mesh",
    "stack_region_batches",
    "run_sharded_partial_agg",
    "run_sharded_grouped_agg",
    "hash_partition_ids",
    "exchange_group_aggregate",
]
