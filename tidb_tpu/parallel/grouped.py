"""Grouped aggregation over the mesh — the MPP partial/exchange/final
pipeline as ONE shard_map program (ref: unistore/cophandler/mpp_exec.go
aggExec:999 below exchSenderExec:609, receiver-side final agg above
exchRecvExec:723; fragment planning pkg/planner/core/fragment.go:116).

Per device, in a single fused XLA computation:
  1. flatten the device's local regions into one row block, run the scan
     expressions + selection,
  2. Partial1 group aggregation (sort/segment kernel) -> a local group-state
     table [G_local],
  3. hash-partition the group states by group key and `all_to_all` them over
     the ICI mesh — every device ends up owning one hash partition of the
     global group space (ref: ExchangeSender Hash mode, fnv64 row hash),
  4. merge-mode group aggregation over the owned states -> FINAL values for
     the owned groups. No host round-trip between phases.

The host wrapper gathers the per-device final tables and decodes one result
Chunk. Group keys AND string aggregate values (min/max/first_row over
varchar) travel as packed compare words (first 32 bytes — the SQL gate
rejects wider string columns)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from .compat import shard_map
from jax.sharding import PartitionSpec as P

from ..chunk.device import DeviceBatch
from ..exec.dag import Aggregation, DAGRequest, Selection
from ..expr.compile import CompVal, ExprCompiler, normalize_device_column
from ..ops import apply_selection, group_aggregate
from ..ops.aggregate import GatherState, finalize_agg
from ..mpp.exchange_op import exchange_arrays, hash_partition_ids
from .mesh import REGION_AXIS


def _flatten_local(local: DeviceBatch):
    """[R_local, cap] region-stacked batch -> flat [R_local*cap] columns."""
    cols = []
    for c in local.cols:
        data = c.data.reshape((-1,) + c.data.shape[2:])
        null = c.null.reshape(-1)
        length = c.length.reshape(-1) if c.length is not None else None
        cols.append(type(c)(data, null, length, c.ft))
    return cols, local.row_valid.reshape(-1)


def _materialize_gather(desc, arg_vals, st: GatherState, final: bool = False):
    """GatherState -> concrete state columns. Partial form keeps the
    [has, value] wire schema for first_row; `final` collapses to the single
    result column. String values (first_row/min/max over varchar) ride the
    exchange as their packed compare words [G, W+1] — decode_outputs
    reconstructs the bytes, so strings up to STRING_WORDS*8 bytes survive
    (the SQL gate parallel/sql.py rejects wider columns)."""
    vcol = arg_vals[-1]
    if vcol.value.ndim == 2:
        val = jnp.where(st.has[:, None], vcol.value[st.idx, :], jnp.zeros((), vcol.value.dtype))
    else:
        val = jnp.where(st.has, vcol.value[st.idx], jnp.zeros((), vcol.value.dtype))
    null = jnp.where(st.has, vcol.null[st.idx], True)
    if desc.name == "first_row" and not final:
        return [(st.has.astype(jnp.int64), jnp.zeros(st.has.shape, bool)), (val, null)]
    return [(val, null)]


def agg_exchange_phases(agg, schema_fts, cvals, valid, n_parts: int, group_capacity: int, bcap: int, extra_overflow=None):
    """The MPP partial/exchange/final pipeline given the pre-agg schema —
    phases 1-3 of the module docstring. Called inside shard_map by both the
    scan+sel path (run_sharded_grouped_agg) and the hash-shuffle join path
    (joinmesh.run_sharded_join_agg). Returns the flat output tuple
    [group_valid, (value, null)*, overflow]."""
    comp = ExprCompiler(schema_fts)
    gvals = comp.run(list(agg.group_by), cvals)
    arg_exprs = [a for d in agg.aggs for a in d.args]
    avals = comp.run(arg_exprs, cvals) if arg_exprs else []
    aggs = []
    k = 0
    for d in agg.aggs:
        aggs.append((d, avals[k : k + len(d.args)]))
        k += len(d.args)

    if any(d.distinct for d in agg.aggs):
        # DISTINCT is not state-decomposable, but it IS local-exact after
        # the group-key shuffle: every group lands whole on one device
        # (the reference's MPP plan for distinct aggs shuffles raw rows by
        # group key then aggregates Complete-mode on the owner —
        # planner/core/task.go agg-over-exchange with one phase)
        return _distinct_exchange_phases(
            agg, gvals, aggs, valid, n_parts, group_capacity, bcap, extra_overflow
        )

    # -- phase 1: local Partial1 ------------------------------------
    res = group_aggregate(gvals, aggs, valid, group_capacity, merge=False)
    p1_overflow = res.overflow
    state_cols: list[tuple] = []  # flat (value, null) per state column
    state_fts: list = []
    for (d, av), st in zip(aggs, res.states):
        if isinstance(st, GatherState):
            mat = _materialize_gather(d, av, st)
        else:
            mat = st
        state_cols.extend(mat)
        state_fts.extend(d.partial_fts())
    gkey_cols = []
    for gv in gvals:
        if gv.value.ndim == 2:
            gkey_cols.append((gv.value[res.group_rep, :], gv.null[res.group_rep]))
        else:
            gkey_cols.append((gv.value[res.group_rep], gv.null[res.group_rep]))
    gvalid = res.group_valid

    # -- phase 2: hash-exchange the group-state rows (exchange_op) ----
    key_cvs = [
        CompVal(v, nl, g.ft) for (v, nl), g in zip(gkey_cols, agg.group_by)
    ]
    part = hash_partition_ids(key_cvs, n_parts)
    flat_arrays = [a for v, nl in state_cols + gkey_cols for a in (v, nl)]
    flat, fvalid, ex_overflow = exchange_arrays(flat_arrays, gvalid, part, n_parts, bcap)

    # -- phase 3: merge-mode aggregation on the owned partition ------
    n_state = len(state_cols)
    it = iter(range(0, 2 * n_state, 2))
    owned_states = [(flat[i], flat[i + 1].astype(bool)) for i in it]
    base = 2 * n_state
    owned_gkeys = [
        CompVal(flat[base + 2 * j], flat[base + 2 * j + 1].astype(bool), g.ft)
        for j, g in enumerate(agg.group_by)
    ]
    merge_aggs = []
    si = 0
    for d, _ in aggs:
        n = len(d.partial_fts())
        args = [
            CompVal(owned_states[si + i][0], owned_states[si + i][1], state_fts[si + i])
            for i in range(n)
        ]
        merge_aggs.append((d, args))
        si += n
    fin = group_aggregate(owned_gkeys, merge_aggs, fvalid, group_capacity, merge=True)
    f_overflow = fin.overflow

    out_cols = []
    for (d, av), st in zip(merge_aggs, fin.states):
        if isinstance(st, GatherState):
            st = GatherState(st.idx, st.has & fin.group_valid)
            out_cols.extend(_materialize_gather(d, av, st, final=True))
        else:
            v, nl = finalize_agg(d, st, fin.group_valid)
            out_cols.append((v, nl))
    for gk in owned_gkeys:
        if gk.value.ndim == 2:
            out_cols.append((gk.value[fin.group_rep, :], gk.null[fin.group_rep] | ~fin.group_valid))
        else:
            out_cols.append((gk.value[fin.group_rep], gk.null[fin.group_rep] | ~fin.group_valid))
    local_ovf = p1_overflow | ex_overflow | f_overflow
    if extra_overflow is not None:
        local_ovf = local_ovf | extra_overflow
    overflow = jax.lax.pmax(local_ovf.astype(jnp.int32), REGION_AXIS) > 0
    flat_out = [a for v, nl in out_cols for a in (v, nl)]
    return tuple([fin.group_valid] + flat_out + [overflow])


def _distinct_exchange_phases(agg, gvals, aggs, valid, n_parts: int, group_capacity: int, bcap: int, extra_overflow=None):
    """Raw-row exchange + Complete-mode owner aggregation (DISTINCT path).

    Exchanges (group keys ++ agg args) row-wise instead of partial states;
    the owner runs the single-device group kernel in Complete mode, whose
    hash-distinct machinery (ops/aggregate.py _distinct_states) is exact.
    Output layout matches agg_exchange_phases."""
    part = hash_partition_ids(gvals, n_parts)
    row_cvs = list(gvals) + [a for _, avs in aggs for a in avs]
    flat_arrays = [a for cv in row_cvs for a in (cv.value, cv.null)]
    flat, fvalid, ex_overflow = exchange_arrays(flat_arrays, valid, part, n_parts, bcap)

    k = 0
    owned: list[CompVal] = []
    for cv in row_cvs:
        owned.append(CompVal(flat[k], flat[k + 1].astype(bool), cv.ft))
        k += 2
    o_gvals = owned[: len(gvals)]
    o_args = owned[len(gvals):]
    o_aggs = []
    ai = 0
    for d, avs in aggs:
        o_aggs.append((d, o_args[ai : ai + len(avs)]))
        ai += len(avs)
    fin = group_aggregate(o_gvals, o_aggs, fvalid, group_capacity, merge=False)

    out_cols = []
    for (d, av), st in zip(o_aggs, fin.states):
        if isinstance(st, GatherState):
            st = GatherState(st.idx, st.has & fin.group_valid)
            out_cols.extend(_materialize_gather(d, av, st, final=True))
        else:
            v, nl = finalize_agg(d, st, fin.group_valid)
            out_cols.append((v, nl))
    for gk in o_gvals:
        if gk.value.ndim == 2:
            out_cols.append((gk.value[fin.group_rep, :], gk.null[fin.group_rep] | ~fin.group_valid))
        else:
            out_cols.append((gk.value[fin.group_rep], gk.null[fin.group_rep] | ~fin.group_valid))
    local_ovf = ex_overflow | fin.overflow
    if extra_overflow is not None:
        local_ovf = local_ovf | extra_overflow
    overflow = jax.lax.pmax(local_ovf.astype(jnp.int32), REGION_AXIS) > 0
    flat_out = [a for v, nl in out_cols for a in (v, nl)]
    return tuple([fin.group_valid] + flat_out + [overflow])


def run_sharded_grouped_agg(
    dag: DAGRequest,
    stacked: DeviceBatch,
    mesh,
    group_capacity: int = 1024,
    bucket_cap: int | None = None,
):
    """Execute TableScan [Selection] Aggregation(group_by) over a
    region-sharded mesh; returns (chunk, overflow flag).

    The Aggregation node is taken as the LOGICAL (Complete-mode) shape; the
    partial/final split happens inside. Output chunk layout matches the
    single-chip executor: [agg results..., group keys...]."""
    executors = dag.executors
    agg = executors[-1]
    assert isinstance(agg, Aggregation) and agg.group_by, "grouped mesh agg needs GROUP BY"
    if any(d.name == "group_concat" for d in agg.aggs):
        raise NotImplementedError("group_concat on mesh (root-only, oracle-evaluated)")
    input_fts = [c.ft for c in dag.scan().columns]
    n_parts = mesh.devices.size
    bcap = bucket_cap or group_capacity

    def device_fn(local: DeviceBatch):
        cols, valid = _flatten_local(local)
        cvals = [normalize_device_column(c) for c in cols]
        for ex in executors[1:-1]:
            comp = ExprCompiler(input_fts)
            if isinstance(ex, Selection):
                conds = comp.run(list(ex.conditions), cvals)
                valid = apply_selection(valid, conds)
            else:
                raise TypeError(f"mesh pipeline supports scan+selection+agg, got {ex}")
        return agg_exchange_phases(agg, input_fts, cvals, valid, n_parts, group_capacity, bcap)

    spec_batch = jax.tree.map(lambda _: P(REGION_AXIS), stacked)
    from ..mpp.exchange_op import cached_exchange_program
    from .mesh import decode_group_mesh_outputs, group_mesh_out_spec

    fn = cached_exchange_program(
        dag, mesh,
        lambda: shard_map(device_fn, mesh=mesh, in_specs=(spec_batch,),
                          out_specs=group_mesh_out_spec(agg), check_vma=False),
        group_capacity, bcap)
    outs = fn(stacked)
    # decode: [agg results..., group keys...] with Complete-mode fts —
    # the shared seam (mesh.py) both grouped paths use
    return decode_group_mesh_outputs(outs, agg)
