"""JAX version shims for the mesh modules — the implementations live in
util.jaxcompat (dependency-free, shared with the ops kernels); this module
keeps the established import path for the mesh call sites."""

from __future__ import annotations

from ..util.jaxcompat import shard_map  # noqa: F401
