"""Compatibility shim — the exchange operator moved to the MPP subsystem
(ISSUE 18): `tidb_tpu/mpp/exchange_op.py` is the one home of the hash
partitioner, the scatter/all_to_all/flatten sequence and the exchange modes
(hash / broadcast / passthrough). This module keeps the historical import
path for the mesh-tier callers and tests."""

from __future__ import annotations

from ..mpp.exchange_op import (  # noqa: F401 — re-exports
    FNV_OFFSET,
    FNV_PRIME,
    broadcast_exchange,
    exchange_arrays,
    exchange_compvals,
    exchange_group_aggregate,
    hash_partition_ids,
    passthrough_exchange,
    scatter_to_buckets,
)

__all__ = [
    "FNV_OFFSET",
    "FNV_PRIME",
    "broadcast_exchange",
    "exchange_arrays",
    "exchange_compvals",
    "exchange_group_aggregate",
    "hash_partition_ids",
    "passthrough_exchange",
    "scatter_to_buckets",
]
