"""Hash-partition exchange over the mesh — the MPP shuffle analog.

The reference's ExchangeSender hash-partitions rows by fnv64 over the
encoded partition keys into per-task tunnels, and ExchangeReceiver merges
the streams (ref: unistore/cophandler/mpp_exec.go:609-841 exchSenderExec /
exchRecvExec; partition modes :669-719). On TPU the tunnels are a single
`jax.lax.all_to_all` over the mesh axis: each device scatters its rows into
P send buckets by key hash, the collective transposes buckets across
devices, and every device ends up owning one hash partition — then local
group aggregation (or join build/probe) runs on owned rows only.

This is the sequence the scaling-book recipe calls "annotate shardings, let
XLA insert collectives": the all_to_all is explicit here because the
partition function is data-dependent (hash of key values).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..expr.compile import CompVal
from ..ops.keys import sort_key_arrays

FNV_OFFSET = np.int64(-3750763034362895579)  # 0xcbf29ce484222325 as i64; numpy: import-time pure
FNV_PRIME = np.int64(1099511628211)


def hash_partition_ids(key_vals: list[CompVal], n_parts: int) -> jax.Array:
    """Row -> partition id in [0, n_parts) from an FNV-style hash over the
    normalized key words (NULL hashes to partition of its zeroed words —
    all NULLs land together, as the reference's encoded-datum hash does)."""
    h = jnp.broadcast_to(FNV_OFFSET, key_vals[0].null.shape)
    for kv in key_vals:
        for w in sort_key_arrays(kv):
            if jnp.issubdtype(w.dtype, jnp.floating):
                # real keys stay float in sort_key_arrays (TPU x64 emulation
                # can't bitcast f64<->s64); a f32 bitcast is supported and
                # equal doubles hash equal, which is all partitioning needs
                w = jax.lax.bitcast_convert_type(w.astype(jnp.float32), jnp.int32).astype(jnp.int64)
            h = (h ^ w) * FNV_PRIME
    # avoid negative mod
    return jnp.abs(h % n_parts).astype(jnp.int32)


def scatter_to_buckets(cols: list[jax.Array], valid: jax.Array, part: jax.Array, n_parts: int, bucket_cap: int):
    """Pack rows into [n_parts, bucket_cap] send buffers by partition id.

    Position within a bucket = rank of the row among same-partition rows
    (prefix count). Returns (bucketed cols, bucket valid, overflow flag).
    """
    n = valid.shape[0]
    part = jnp.where(valid, part, n_parts)  # invalid rows -> ghost bucket
    onehot = part[:, None] == jnp.arange(n_parts + 1)[None, :]  # [n, P+1]
    rank = jnp.cumsum(onehot, axis=0) - 1  # rank within partition
    pos_in_bucket = jnp.take_along_axis(rank, part[:, None], axis=1)[:, 0]
    counts = onehot.sum(axis=0)[:n_parts]
    overflow = jnp.any(counts > bucket_cap)
    flat_pos = part * bucket_cap + jnp.minimum(pos_in_bucket, bucket_cap - 1)
    total = (n_parts + 1) * bucket_cap

    out_valid = jnp.zeros(total, bool).at[flat_pos].set(valid & (pos_in_bucket < bucket_cap))
    out_cols = []
    for c in cols:
        buf = jnp.zeros((total,) + c.shape[1:], c.dtype)
        buf = buf.at[flat_pos].set(c)
        out_cols.append(buf.reshape((n_parts + 1, bucket_cap) + c.shape[1:])[:n_parts])
    return out_cols, out_valid.reshape(n_parts + 1, bucket_cap)[:n_parts], overflow


def broadcast_exchange(mesh_axis: str, cols: list, valid):
    """Broadcast mode (ref: mpp_exec.go:669 Broadcast partition type, the
    TiFlash broadcast-join operand path): every device receives EVERY row.
    Returns ([P*n]-shaped cols, valid) identical on all devices — one
    all_gather over ICI per column."""
    out_cols = []
    for c in cols:
        g = jax.lax.all_gather(c, mesh_axis, axis=0, tiled=False)  # [P, n, ...]
        out_cols.append(g.reshape((-1,) + c.shape[1:]))
    gv = jax.lax.all_gather(valid, mesh_axis, axis=0, tiled=False).reshape(-1)
    return out_cols, gv


def passthrough_exchange(mesh_axis: str, cols: list, valid, target: int = 0):
    """PassThrough mode (ref: mpp_exec.go:669-719 PassThrough partition
    type — the root-gather: every task streams all rows to the single
    collector). All devices' rows land on `target`; other devices keep the
    buffers (SPMD static shapes) with all-False validity."""
    out_cols, gv = broadcast_exchange(mesh_axis, cols, valid)
    me = jax.lax.axis_index(mesh_axis)
    gv = gv & (me == target)
    return out_cols, gv


def exchange_group_aggregate(mesh_axis: str, key_vals, agg_fn, cols, valid, n_parts: int, bucket_cap: int):
    """Inside shard_map: hash-exchange rows so each device owns one hash
    partition, then run `agg_fn(owned_cols, owned_valid)` locally.

    agg_fn receives rows of shape [n_parts * bucket_cap] (all rows of this
    device's partition gathered from every peer).
    """
    part = hash_partition_ids(key_vals, n_parts)
    bcols, bvalid, overflow = scatter_to_buckets(cols, valid, part, n_parts, bucket_cap)
    # all_to_all: dim0 currently indexes destination partition; after the
    # collective it indexes source device, and this device holds only its
    # own partition's rows (ref: ExchangerTunnel per-task streams)
    recv_cols = [jax.lax.all_to_all(c, mesh_axis, 0, 0, tiled=False) for c in bcols]
    recv_valid = jax.lax.all_to_all(bvalid, mesh_axis, 0, 0, tiled=False)
    flat_cols = [c.reshape((-1,) + c.shape[2:]) for c in recv_cols]
    flat_valid = recv_valid.reshape(-1)
    overflow = jax.lax.pmax(overflow.astype(jnp.int32), mesh_axis) > 0
    return agg_fn(flat_cols, flat_valid), overflow
