"""Hash-shuffle (repartition) join over the mesh — thin wrapper over the
MPP exchange data plane (ISSUE 18).

The device program — hash-partition BOTH join sides by the join-key hash,
`all_to_all` them over the ICI mesh, join each owned partition locally,
aggregate above (ref: unistore/cophandler/mpp_exec.go:609-721
exchSenderExec Hash mode with joinExec:844 above the receivers) — lives in
`mpp/exchange_op.py` (`run_exchange_join_agg`), and the DAG splitter that
proves the chain shape lives with the fragment planner
(`mpp/fragment.py` `split_join_dag`, re-exported here for the historical
import path). This module keeps the mesh-tier entry point only."""

from __future__ import annotations

from ..mpp.fragment import split_join_dag  # noqa: F401 — re-export

__all__ = ["split_join_dag", "run_sharded_join_agg"]


def run_sharded_join_agg(
    dag,
    stacked_probe,
    stacked_builds: list,
    mesh,
    group_capacity: int = 1024,
    scale: int = 1,
):
    """Execute scan [sel] (JOIN(scan [sel]) [sel])+ GROUP BY over the mesh;
    returns (chunk, overflow flag). Delegates to the exchange operator —
    one shuffle-join program serves the mesh tier and the mpp tier."""
    from ..mpp.exchange_op import run_exchange_join_agg

    return run_exchange_join_agg(
        dag, stacked_probe, stacked_builds, mesh,
        group_capacity=group_capacity, scale=scale,
    )
