"""Hash-shuffle (repartition) join over the mesh — the MPP join analog
(ref: unistore/cophandler/mpp_exec.go:609-721 exchSenderExec Hash mode with
joinExec:844 above the receivers; fragment planning fragment.go:116).

The reference hash-partitions BOTH join sides by the join-key hash across
TiFlash nodes, joins each partition locally, and aggregates above. The TPU
shape, as ONE shard_map program per device:

  1. flatten the device's local probe regions / build slices, run the
     scan expressions + pre-join selections on each side;
  2. hash-partition both sides by their join keys and `all_to_all` them
     over the ICI mesh — equal keys land on the same device because both
     sides hash the same normalized key words (the planner unified the key
     types, like the reference's hash-join key normalization);
  3. local hash join (ops/join.py kernel) on the owned partition, then any
     post-join selections;
  4. grouped aggregation Partial1 -> group-key exchange -> Final merge —
     the same phases as grouped.py (shared agg_exchange_phases).

String payload columns ride the exchange as packed compare words (the SQL
gate rejects strings wider than the word budget, parallel/sql.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..chunk.device import DeviceBatch
from ..exec.dag import Aggregation, DAGRequest, Join, Selection, TableScan
from ..expr.compile import CompVal, ExprCompiler, normalize_device_column
from ..ops import apply_selection
from ..ops.join import hash_join
from .exchange import hash_partition_ids, scatter_to_buckets
from .grouped import _flatten_local, agg_exchange_phases
from .mesh import REGION_AXIS


def split_join_dag(dag: DAGRequest):
    """-> (probe_scan, pre_sels, [(join, post_sels), ...], agg) or None.

    A CHAIN of shuffle joins is eligible (TPC-H Q3's 3-table shape:
    lineitem ⋈ orders ⋈ customer — each stage re-exchanges the widened
    schema by the next join key, ref: fragment.go stacking ExchangeSender
    under each HashJoin). Build sides must be scan [selection]* — a join
    nested INSIDE a build side still stays off-mesh; the planner
    right-deepens chains so that shape is the common one."""
    exs = dag.executors
    if not exs or not isinstance(exs[0], TableScan):
        return None
    i = 1
    pre = []
    while i < len(exs) and isinstance(exs[i], Selection):
        pre.append(exs[i])
        i += 1
    stages = []
    while i < len(exs) and isinstance(exs[i], Join):
        join = exs[i]
        i += 1
        post = []
        while i < len(exs) and isinstance(exs[i], Selection):
            post.append(exs[i])
            i += 1
        if not join.build or not isinstance(join.build[0], TableScan):
            return None
        if not all(isinstance(e, Selection) for e in join.build[1:]):
            return None
        stages.append((join, post))
    if not stages or i != len(exs) - 1 or not isinstance(exs[i], Aggregation):
        return None
    return exs[0], pre, stages, exs[i]


def _exchange_side(cvals: list[CompVal], valid, part, n_parts: int, bucket_cap: int):
    """all_to_all one side's rows by partition id; returns (cvals, valid,
    overflow) for the owned partition."""
    flat = [a for c in cvals for a in (c.value, c.null)]
    bufs, bvalid, ovf = scatter_to_buckets(flat, valid, part, n_parts, bucket_cap)
    recv = [jax.lax.all_to_all(b, REGION_AXIS, 0, 0, tiled=False) for b in bufs]
    rvalid = jax.lax.all_to_all(bvalid, REGION_AXIS, 0, 0, tiled=False)
    flat_r = [r.reshape((-1,) + r.shape[2:]) for r in recv]
    out = [
        CompVal(flat_r[2 * i], flat_r[2 * i + 1].astype(bool), c.ft)
        for i, c in enumerate(cvals)
    ]
    return out, rvalid.reshape(-1), ovf


def _gather_cv(cols: list[CompVal], idx) -> list[CompVal]:
    out = []
    for c in cols:
        if c.value.ndim == 2:
            out.append(CompVal(c.value[idx, :], c.null[idx], c.ft))
        else:
            out.append(CompVal(c.value[idx], c.null[idx], c.ft))
    return out


def run_sharded_join_agg(
    dag: DAGRequest,
    stacked_probe: DeviceBatch,
    stacked_builds: list,
    mesh,
    group_capacity: int = 1024,
    scale: int = 1,
):
    """Execute scan [sel] (JOIN(scan [sel]) [sel])+ GROUP BY over the mesh;
    returns (chunk, overflow flag). Output layout matches the single-chip
    executor: [agg results..., group keys...]. Multi-join chains (TPC-H
    Q3) re-exchange the widened probe schema at every stage by that
    stage's join key.

    Exchange buckets are sized ~2x the per-device fair share (total/n) so
    per-device post-exchange work stays ~1/n of the table — the point of
    the repartition; `scale` (grown by the caller's overflow retry)
    multiplies every data-dependent capacity: exchange buckets for skewed
    keys and the join out-capacity for fan-out > 1."""
    parts = split_join_dag(dag)
    assert parts is not None, "not a shuffle-join DAG shape"
    probe_scan, pre_sels, stages, agg = parts
    if not isinstance(stacked_builds, (list, tuple)):
        stacked_builds = [stacked_builds]
    assert len(stacked_builds) == len(stages), "one build batch per join stage"
    pfts = [c.ft for c in probe_scan.columns]
    n_parts = mesh.devices.size

    def device_fn(lp: DeviceBatch, *lbs):
        pcols, pvalid = _flatten_local(lp)
        pc = [normalize_device_column(c) for c in pcols]
        for ex in pre_sels:
            conds = ExprCompiler(pfts).run(list(ex.conditions), pc)
            pvalid = apply_selection(pvalid, conds)
        # drop raw string bytes: only packed words cross the exchange
        pc = [CompVal(c.value, c.null, c.ft) for c in pc]
        schema = list(pfts)
        valid = pvalid
        cols = pc
        extra = jnp.bool_(False)

        for (join, post_sels), lb in zip(stages, lbs):
            bfts = [c.ft for c in join.build[0].columns]
            bcols, bvalid = _flatten_local(lb)
            bc = [normalize_device_column(c) for c in bcols]
            for ex in join.build[1:]:
                conds = ExprCompiler(bfts).run(list(ex.conditions), bc)
                bvalid = apply_selection(bvalid, conds)
            bc = [CompVal(c.value, c.null, c.ft) for c in bc]

            # hash-partition both sides by THIS stage's join key
            pkeys = ExprCompiler(schema).run(list(join.probe_keys), cols)
            bkeys = ExprCompiler(bfts).run(list(join.build_keys), bc)
            pcap = max(64, 2 * scale * valid.shape[0] // n_parts)
            bcap_ = max(64, 2 * scale * bvalid.shape[0] // n_parts)
            pp = hash_partition_ids(pkeys, n_parts)
            bp = hash_partition_ids(bkeys, n_parts)
            pc2, pvalid2, povf = _exchange_side(cols, valid, pp, n_parts, pcap)
            bc2, bvalid2, bovf = _exchange_side(bc, bvalid, bp, n_parts, bcap_)

            # local join on the owned partition (ref: joinExec above receivers)
            pkeys2 = ExprCompiler(schema).run(list(join.probe_keys), pc2)
            bkeys2 = ExprCompiler(bfts).run(list(join.build_keys), bc2)
            res = hash_join(
                bkeys2, pkeys2, bvalid2, pvalid2,
                out_capacity=scale * pvalid2.shape[0],
                join_type=join.join_type,
                build_unique=join.build_unique,
            )
            extra = extra | povf | bovf | res.overflow
            if join.join_type in ("semi", "anti"):
                cols = pc2
                valid = res.out_valid
            else:
                nb = bvalid2.shape[0]
                p_g = pc2 if res.probe_identity else _gather_cv(pc2, res.probe_idx)
                b_g = _gather_cv(bc2, jnp.clip(res.build_idx, 0, nb - 1))
                b_g = [CompVal(c.value, c.null | res.build_null, c.ft) for c in b_g]
                cols = p_g + b_g
                valid = res.out_valid
                schema = schema + (
                    [f.clone_nullable() for f in bfts]
                    if join.join_type == "left_outer" else bfts
                )
            for ex in post_sels:
                conds = ExprCompiler(schema).run(list(ex.conditions), cols)
                valid = apply_selection(valid, conds)

        return agg_exchange_phases(
            agg, schema, cols, valid, n_parts, group_capacity,
            group_capacity, extra_overflow=extra,
        )

    from .compat import shard_map
    from jax.sharding import PartitionSpec as P

    from .mesh import decode_group_mesh_outputs, group_mesh_out_spec

    spec_p = jax.tree.map(lambda _: P(REGION_AXIS), stacked_probe)
    spec_bs = tuple(jax.tree.map(lambda _: P(REGION_AXIS), sb) for sb in stacked_builds)
    fn = shard_map(device_fn, mesh=mesh, in_specs=(spec_p, *spec_bs), out_specs=group_mesh_out_spec(agg), check_vma=False)
    outs = jax.jit(fn)(stacked_probe, *stacked_builds)
    # decode via the shared seam (mesh.py) — same layout as grouped.py
    return decode_group_mesh_outputs(outs, agg)
