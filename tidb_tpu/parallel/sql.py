"""SQL -> mesh fragmentation: route eligible pushdown plans onto the
device mesh (ref: pkg/planner/core/fragment.go:116 GenerateRootMPPTasks —
the reference cuts physical plans at exchange boundaries into per-node MPP
tasks; here the cut is scan+selection below, grouped aggregation above,
with the hash exchange inside run_sharded_grouped_agg).

The decision mirrors the reference's `useMPPExecution` gate
(pkg/executor/mpp_gather.go:40, sysvar TiDBAllowMPPExecution): the session
asks `try_mesh_select` first; a None return (ineligible shape, too few
devices, group overflow) falls back to the per-region thread-pool path, the
same way the reference falls back from TiFlash MPP to cop tasks.
"""

from __future__ import annotations

from ..chunk import Chunk
from ..distsql.dispatch import KVRequest, select
from ..exec.dag import Aggregation, DAGRequest, Selection, TableScan

MESH_SYSVAR = "tidb_enable_tpu_mesh"
# packed compare words carry the first STRING_WORDS*8 bytes across the
# exchange; longer strings would silently truncate, so they stay off-mesh.
# flen counts CHARACTERS (utf8mb4: up to 4 bytes each) and inserts do not
# enforce it, so the static gate is advisory only — the authoritative check
# measures actual bytes in the scanned chunks (_chunks_exchange_safe).
_MAX_EXCH_STR = 32


def _chunks_exchange_safe(chunks) -> bool:
    """No string value in any scanned column exceeds the packed-word width
    the exchange can carry byte-exactly."""
    for c in chunks:
        for col in c.columns:
            if col.is_varlen() and len(col):
                if int((col.offsets[1:] - col.offsets[:-1]).max()) > _MAX_EXCH_STR:
                    return False
    return True


def _agg_mesh_ok(agg) -> bool:
    if not isinstance(agg, Aggregation) or not agg.group_by or agg.merge:
        return False
    # DISTINCT rides the raw-row exchange (grouped.py
    # _distinct_exchange_phases); group_concat stays root-only
    return not any(d.name == "group_concat" for d in agg.aggs)


def mesh_eligible(dag: DAGRequest) -> str | None:
    """Shape gate (ref: the reference's per-operator CanPushToTiFlash
    checks in exhaust_physical_plans). Returns the mesh plan kind:

      "agg"  — TableScan [Selection]* Aggregation(GROUP BY)
      "join" — TableScan [Sel]* Join(scan [Sel]*) [Sel]* Aggregation(...)
               (the hash-shuffle repartition join, joinmesh.py)
      None   — ineligible (host-only exprs, DISTINCT, merge mode, ...)
    """
    from ..distsql.root import host_only_exprs

    exs = dag.executors
    if len(exs) < 2 or not isinstance(exs[0], TableScan):
        return None
    agg = exs[-1]
    if not _agg_mesh_ok(agg):
        return None
    agg_exprs = list(agg.group_by) + [a for d in agg.aggs for a in d.args]

    if all(isinstance(e, Selection) for e in exs[1:-1]):
        exprs = [c for e in exs[1:-1] for c in e.conditions] + agg_exprs
        # the device ExprCompiler cannot trace host-only ops (json_*,
        # regexp, extensions) — the thread-pool path keeps them at root, so
        # the mesh path must refuse them rather than fail inside the trace
        return None if host_only_exprs(exprs) else "agg"

    from .joinmesh import split_join_dag

    parts = split_join_dag(dag)
    if parts is None:
        return None
    _, pre, stages, _ = parts
    exprs = [c for e in pre for c in e.conditions] + agg_exprs
    for join, post in stages:
        exprs += [c for e in list(join.build[1:]) + post for c in e.conditions]
        exprs += list(join.probe_keys) + list(join.build_keys)
    if host_only_exprs(exprs):
        return None
    return "join"


def try_mesh_select(
    store,
    dag: DAGRequest,
    ranges: list,
    start_ts: int,
    group_capacity: int = 1024,
    min_devices: int = 2,
    aux_chunks: list | None = None,
) -> Chunk | None:
    """Execute an eligible plan over the region mesh; None = not taken.

    Region rows reach the devices through the same scan pushdown
    (paging/retry preserved) as the thread-pool path; the plan then runs
    as ONE shard_map program: either Partial1 -> all_to_all hash exchange
    -> Final (parallel/grouped.py) or the hash-shuffle repartition join
    feeding the same phases (parallel/joinmesh.py). aux_chunks carries the
    materialized build table for join plans (sliced across devices — each
    slice plays a region shard)."""
    kind = mesh_eligible(dag)
    if kind is None:
        return None
    if kind == "join" and not aux_chunks:
        return None
    import jax

    devs = jax.devices()
    if len(devs) < min_devices:
        return None
    from ..util import tracing

    with tracing.span("parallel.mesh_select", kind=kind, n_devices=len(devs),
                      n_ranges=len(ranges)) as sp:
        out = _mesh_select(store, dag, ranges, start_ts, group_capacity, aux_chunks, kind, devs)
        if sp is not None and out is not None:
            sp.set("rows", out.num_rows())
        return out


def _mesh_select(store, dag, ranges, start_ts, group_capacity, aux_chunks, kind, devs) -> Chunk | None:
    from .grouped import run_sharded_grouped_agg
    from .mesh import region_mesh, stack_region_batches

    scan = dag.executors[0]
    scan_dag = DAGRequest((scan,), output_offsets=tuple(range(len(scan.columns))))
    res = select(store, KVRequest(scan_dag, ranges, start_ts))
    chunks = [c for c in res.chunks if c is not None and c.num_rows() > 0]
    agg = dag.executors[-1]
    out_fts = agg.output_fts()
    if not chunks:
        # zero rows scanned: grouped aggregation of nothing is no groups
        return Chunk.empty([out_fts[i] for i in dag.output_offsets])
    if not _chunks_exchange_safe(chunks):
        return None  # wide strings cannot ride the exchange byte-exactly

    n = len(devs)
    n_total = ((len(chunks) + n - 1) // n) * n
    try:
        stacked = stack_region_batches(chunks, n_total=n_total)
    except NotImplementedError:
        return None  # e.g. non-ASCII CI data: the per-region path's
        # oracle fallback owns it (chunk/device.py guard)
    mesh = region_mesh(n)

    stacked_builds = None
    if kind == "join":
        from .joinmesh import split_join_dag

        n_stages = len(split_join_dag(dag)[2])
        if len(aux_chunks) < n_stages:
            return None
        stacked_builds = []
        for build in aux_chunks[:n_stages]:
            if not _chunks_exchange_safe([build]):
                return None
            if build.num_rows() == 0:
                bslices = [build]
            else:
                step = (build.num_rows() + n - 1) // n
                bslices = [
                    build.slice(i * step, min((i + 1) * step, build.num_rows()))
                    for i in range(n)
                    if i * step < build.num_rows()
                ]
            try:
                stacked_builds.append(stack_region_batches(bslices, n_total=n))
            except NotImplementedError:
                return None  # non-ASCII CI build data -> per-region path

    # overflow (too many groups / join fan-out / hash collision): retry
    # with 4x capacity — the capacity also salts the hash, mirroring
    # drive_program's contract — reusing the scanned chunks, not rescanning
    gc = group_capacity
    scale = 1
    for _ in range(3):
        try:
            if kind == "join":
                from .joinmesh import run_sharded_join_agg

                chunk, overflow = run_sharded_join_agg(
                    dag, stacked, stacked_builds, mesh, group_capacity=gc, scale=scale
                )
            else:
                chunk, overflow = run_sharded_grouped_agg(dag, stacked, mesh, group_capacity=gc)
        except NotImplementedError:
            # an op the device compiler refuses slipped past the static
            # gate: fall back to the per-region thread-pool path, which
            # keeps host-only work at root (mirrors store.coprocessor's
            # oracle fallback)
            return None
        if not overflow:
            from ..util import metrics

            metrics.MESH_SELECTS.inc()
            cols = [chunk.columns[i] for i in dag.output_offsets]
            return Chunk(cols)
        # one overflow flag covers groups, exchange buckets, and join
        # fan-out: grow every data-dependent capacity together
        gc *= 4
        scale *= 4
    return None  # caller falls back to the per-region path
