"""SQL -> mesh fragmentation: route eligible pushdown plans onto the
device mesh (ref: pkg/planner/core/fragment.go:116 GenerateRootMPPTasks —
the reference cuts physical plans at exchange boundaries into per-node MPP
tasks; here the cut is scan+selection below, grouped aggregation above,
with the hash exchange inside run_sharded_grouped_agg).

The decision mirrors the reference's `useMPPExecution` gate
(pkg/executor/mpp_gather.go:40, sysvar TiDBAllowMPPExecution): the session
asks `try_mesh_select` first; a None return (ineligible shape, too few
devices, group overflow) falls back to the per-region thread-pool path, the
same way the reference falls back from TiFlash MPP to cop tasks.
"""

from __future__ import annotations

from ..chunk import Chunk
from ..distsql.dispatch import KVRequest, select
from ..exec.dag import Aggregation, DAGRequest, Selection, TableScan

MESH_SYSVAR = "tidb_enable_tpu_mesh"
# the string width gate is a property of the EXCHANGE, not of this tier —
# it lives with the fragment planner so every exchange consumer (mesh
# shortcut, mpp tier) shares one check (historical aliases kept)
from ..mpp.fragment import MAX_EXCHANGE_STR as _MAX_EXCH_STR  # noqa: E402,F401
from ..mpp.fragment import chunks_exchange_safe as _chunks_exchange_safe  # noqa: E402,F401


def _agg_mesh_ok(agg) -> bool:
    if not isinstance(agg, Aggregation) or not agg.group_by or agg.merge:
        return False
    # DISTINCT rides the raw-row exchange (grouped.py
    # _distinct_exchange_phases); group_concat stays root-only
    return not any(d.name == "group_concat" for d in agg.aggs)


def mesh_eligible(dag: DAGRequest) -> str | None:
    """Shape gate (ref: the reference's per-operator CanPushToTiFlash
    checks in exhaust_physical_plans). Returns the mesh plan kind:

      "agg"  — TableScan [Selection]* Aggregation(GROUP BY)
      "join" — TableScan [Sel]* Join(scan [Sel]*) [Sel]* Aggregation(...)
               (the hash-shuffle repartition join, joinmesh.py)
      None   — ineligible (host-only exprs, DISTINCT, merge mode, ...)
    """
    from ..distsql.root import host_only_exprs

    exs = dag.executors
    if len(exs) < 2 or not isinstance(exs[0], TableScan):
        return None
    agg = exs[-1]
    if not _agg_mesh_ok(agg):
        return None
    agg_exprs = list(agg.group_by) + [a for d in agg.aggs for a in d.args]

    if all(isinstance(e, Selection) for e in exs[1:-1]):
        exprs = [c for e in exs[1:-1] for c in e.conditions] + agg_exprs
        # the device ExprCompiler cannot trace host-only ops (json_*,
        # regexp, extensions) — the thread-pool path keeps them at root, so
        # the mesh path must refuse them rather than fail inside the trace
        return None if host_only_exprs(exprs) else "agg"

    from .joinmesh import split_join_dag

    parts = split_join_dag(dag)
    if parts is None:
        return None
    _, pre, stages, _ = parts
    exprs = [c for e in pre for c in e.conditions] + agg_exprs
    for join, post in stages:
        exprs += [c for e in list(join.build[1:]) + post for c in e.conditions]
        exprs += list(join.probe_keys) + list(join.build_keys)
    if host_only_exprs(exprs):
        return None
    return "join"


def try_mesh_select(
    store,
    dag: DAGRequest,
    ranges: list,
    start_ts: int,
    group_capacity: int = 1024,
    min_devices: int = 2,
    aux_chunks: list | None = None,
) -> Chunk | None:
    """Execute an eligible plan over the region mesh; None = not taken.

    Region rows reach the devices through the same scan pushdown
    (paging/retry preserved) as the thread-pool path; the plan then runs
    as ONE shard_map program: either Partial1 -> all_to_all hash exchange
    -> Final (parallel/grouped.py) or the hash-shuffle repartition join
    feeding the same phases (parallel/joinmesh.py). aux_chunks carries the
    materialized build table for join plans (sliced across devices — each
    slice plays a region shard)."""
    kind = mesh_eligible(dag)
    if kind is None:
        return None
    if kind == "join" and not aux_chunks:
        return None
    import jax

    devs = jax.devices()
    if len(devs) < min_devices:
        return None
    from ..util import tracing

    with tracing.span("parallel.mesh_select", kind=kind, n_devices=len(devs),
                      n_ranges=len(ranges)) as sp:
        out = _mesh_select(store, dag, ranges, start_ts, group_capacity, aux_chunks, kind, devs)
        if sp is not None and out is not None:
            sp.set("rows", out.num_rows())
        return out


def _mesh_select(store, dag, ranges, start_ts, group_capacity, aux_chunks, kind, devs) -> Chunk | None:
    from ..mpp.dispatch import execute_exchange_plan

    scan = dag.executors[0]
    scan_dag = DAGRequest((scan,), output_offsets=tuple(range(len(scan.columns))))
    res = select(store, KVRequest(scan_dag, ranges, start_ts))
    chunks = [c for c in res.chunks if c is not None and c.num_rows() > 0]
    # the stacking / build-slicing / capacity-ladder core is shared with
    # the mpp tier (mpp/dispatch.py) — one launch plan for the exchange
    # program regardless of which tier chose it
    return execute_exchange_plan(dag, chunks, aux_chunks, kind, devs,
                                 group_capacity=group_capacity)
