"""Mesh data parallelism: regions sharded over TPU devices.

The reference fans per-region cop tasks out to store nodes over gRPC
(ref: copr/coprocessor.go:806 worker pool; batch_coprocessor.go groups
regions per store). The TPU-native shape (SURVEY.md §2.5): stack region
batches on a leading axis, shard that axis over a 1-D `jax.sharding.Mesh`,
run the fused DAG per region under `shard_map` + `vmap`, and psum the
partial aggregate states over ICI — the collective replaces the host-side
merge loop, which is the BASELINE.json north star:

    "per-region partial aggregates are psum-reduced over the ICI mesh
     before final merge"
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..chunk import Chunk, to_device_batch
from ..chunk.device import DeviceBatch, DeviceColumn
from ..exec.dag import Aggregation, DAGRequest
from ..expr.compile import ExprCompiler, normalize_device_column
from ..ops import apply_selection, scalar_aggregate

REGION_AXIS = "region"


def region_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (REGION_AXIS,))


def stack_region_batches(chunks: list[Chunk], capacity: int | None = None, n_total: int | None = None) -> DeviceBatch:
    """Stack per-region chunks into one [R, cap] batch.

    All regions pad to a common capacity and common string widths so the
    stacked arrays are rectangular; `n_total` (>= len(chunks)) additionally
    pads the region axis so R is divisible by the mesh size.
    """
    cap = capacity or max(1, max(c.num_rows() for c in chunks))
    # common string width per column
    str_widths: dict[int, int] = {}
    for c in chunks:
        for ci, col in enumerate(c.columns):
            if col.is_varlen():
                w = int((col.offsets[1:] - col.offsets[:-1]).max()) if len(col) else 1
                str_widths[ci] = max(str_widths.get(ci, 1), w)
    batches = [to_device_batch(c, capacity=cap, str_widths=str_widths or None) for c in chunks]
    R = n_total or len(batches)
    while len(batches) < R:
        batches.append(to_device_batch(Chunk.empty(chunks[0].field_types()), capacity=cap, str_widths=str_widths or None))

    def stack(*xs):
        return jnp.stack(xs)

    return jax.tree.map(stack, *batches)


def run_sharded_partial_agg(dag: DAGRequest, stacked: DeviceBatch, mesh: Mesh):
    """Scalar-aggregation pushdown over a region-sharded mesh.

    DAG shape: TableScan [Selection] Aggregation(group_by=(), partial=True).
    Each device: vmap the fused selection over its local regions, reduce the
    partial states across local regions, then psum across the mesh — every
    device ends with the global partial states (the final merge is a single
    host-side finalize).

    Returns list of per-agg state arrays (each [1] after the global merge).
    """
    executors = dag.executors
    agg = executors[-1]
    assert isinstance(agg, Aggregation) and not agg.group_by, "sharded scalar agg only"
    input_fts = [c.ft for c in dag.scan().columns]

    def per_region(cols_and_valid):
        cols, valid = cols_and_valid
        fts = input_fts
        cvals = [normalize_device_column(c) for c in cols]
        for ex in executors[1:-1]:
            comp = ExprCompiler(fts)
            from ..exec.dag import Selection as Sel

            if isinstance(ex, Sel):
                conds = comp.run(list(ex.conditions), cvals)
                valid = apply_selection(valid, conds)
            else:
                raise TypeError(f"sharded pipeline supports scan+selection+agg, got {ex}")
        comp = ExprCompiler(input_fts)
        arg_exprs = [a for desc in agg.aggs for a in desc.args]
        avals = comp.run(arg_exprs, cvals) if arg_exprs else []
        aggs = []
        k = 0
        for desc in agg.aggs:
            aggs.append((desc, avals[k : k + len(desc.args)]))
            k += len(desc.args)
        states = scalar_aggregate(aggs, valid, merge=agg.merge)
        # flatten to arrays: per agg, per state col: (value[1], null[1])
        flat = []
        for st in states:
            for v, nl in st:
                flat.append((v, nl))
        return flat

    # merge op per partial-state column, by aggregate name (the schema in
    # expr/agg.py partial_fts: count->[cnt], sum->[sum], avg->[cnt,sum], ...)
    state_ops: list[str] = []
    for desc in agg.aggs:
        n_states = len(desc.partial_fts())
        if desc.name in ("count", "sum", "avg", "bit_xor"):
            # avg states are [count, sum] — both additive; bit_xor merge is xor
            ops = ["sum"] * n_states if desc.name != "bit_xor" else ["xor"]
        elif desc.name in ("min", "max", "first_row", "bit_and", "bit_or"):
            ops = [desc.name if desc.name in ("min", "max") else
                   ("and" if desc.name == "bit_and" else
                    "or" if desc.name == "bit_or" else "first")] * n_states
        else:
            raise TypeError(f"no mesh merge for aggregate {desc.name!r}")
        state_ops.extend(ops)

    def device_fn(local: DeviceBatch):
        # local: [R_local, cap] pytree
        flat = jax.vmap(lambda c, v: per_region((c, v)))(local.cols, local.row_valid)
        merged = []
        for op, (v, nl) in zip(state_ops, flat):
            merged.append(_merge_state(op, v, nl, REGION_AXIS))
        return merged

    from jax import shard_map

    spec_batch = jax.tree.map(lambda _: P(REGION_AXIS), stacked)
    out_spec = [(P(), P())] * _n_state_cols(agg)
    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(spec_batch,),
        out_specs=out_spec,
        # first/bit states merge via all_gather + identical local reduce:
        # replicated in fact, but not statically inferrable by the vma check
        check_vma=False,
    )
    return jax.jit(fn)(stacked)


def _n_state_cols(agg: Aggregation) -> int:
    return sum(len(d.partial_fts()) for d in agg.aggs)


def _merge_state(op: str, v, nl, axis: str):
    """Merge one partial-state column across local regions then the mesh.

    v: [R_local, 1] values (NULL lanes zeroed), nl: [R_local, 1] null flags.
    NULL means "no rows seen in this region"; the merged state is NULL only
    if every region's is (ref: aggfuncs partial merge semantics). Sum-like
    states ride psum over ICI (the north-star collective); min/max ride
    pmin/pmax; bit/first states all_gather (tiny) and reduce locally.
    """
    allnull = jnp.all(nl, axis=0)
    if op in ("sum", "xor", "or"):
        fill = jnp.zeros((), v.dtype)
    elif op == "and":
        fill = jnp.full((), -1, v.dtype)
    elif op == "min":
        fill = (jnp.full((), jnp.inf, v.dtype) if jnp.issubdtype(v.dtype, jnp.floating)
                else jnp.full((), jnp.iinfo(v.dtype).max, v.dtype))
    elif op == "max":
        fill = (jnp.full((), -jnp.inf, v.dtype) if jnp.issubdtype(v.dtype, jnp.floating)
                else jnp.full((), jnp.iinfo(v.dtype).min, v.dtype))
    else:  # first
        fill = jnp.zeros((), v.dtype)
    masked = jnp.where(nl, fill, v)

    if op == "sum":
        val = jax.lax.psum(jnp.sum(masked, axis=0), axis)
    elif op == "min":
        val = jax.lax.pmin(jnp.min(masked, axis=0), axis)
    elif op == "max":
        val = jax.lax.pmax(jnp.max(masked, axis=0), axis)
    elif op in ("xor", "or", "and"):
        red = {"xor": jnp.bitwise_xor, "or": jnp.bitwise_or, "and": jnp.bitwise_and}[op]
        local = red.reduce(masked, axis=0)
        gathered = jax.lax.all_gather(local, axis)  # [D, 1]
        val = red.reduce(gathered, axis=0)
    else:  # first: first non-null region in global region order
        # global order == device-major: regions were stacked then sharded on
        # the leading axis, so device d owns regions [d*R_local, (d+1)*R_local)
        gv = jax.lax.all_gather(masked, axis).reshape((-1,) + v.shape[1:])
        gn = jax.lax.all_gather(nl, axis).reshape((-1,) + nl.shape[1:])
        idx = jnp.argmax(~gn, axis=0)
        val = jnp.take_along_axis(gv, idx[None], axis=0)[0]
    allnull = jax.lax.pmin(allnull.astype(jnp.int32), axis) > 0
    if op in ("min", "max", "first"):
        val = jnp.where(allnull, jnp.zeros((), v.dtype), val)
    return val, allnull
