"""Mesh data parallelism: regions sharded over TPU devices.

The reference fans per-region cop tasks out to store nodes over gRPC
(ref: copr/coprocessor.go:806 worker pool; batch_coprocessor.go groups
regions per store). The TPU-native shape (SURVEY.md §2.5): stack region
batches on a leading axis, shard that axis over a 1-D `jax.sharding.Mesh`,
run the fused DAG per region under `shard_map` + `vmap`, and psum the
partial aggregate states over ICI — the collective replaces the host-side
merge loop, which is the BASELINE.json north star:

    "per-region partial aggregates are psum-reduced over the ICI mesh
     before final merge"

This module owns the SHARED merge seam: `partial_merge_plan` +
`merge_packed_states` (psum for sum/count/avg/moments, pmin/pmax with the
flipped unsigned domain, all_gather for bit/first states) are consumed both
by the standalone `run_sharded_partial_agg` entry point and by
`exec/builder.py`'s mesh-tier programs, so the standard `distsql.select`
dispatch and the parallel/sql.py mesh_select path merge states with ONE
implementation. Region stacking likewise delegates to the chunk layer's
`to_stacked_device_batch` — the same host-side stacking the batch
coprocessor uses — instead of a second device-side stack.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..chunk import Chunk
from ..chunk.device import DeviceBatch, to_stacked_device_batch
from ..mpp.exchange_op import REGION_AXIS  # canonical home (ISSUE 18)


def region_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (REGION_AXIS,))


def stack_region_batches(chunks: list[Chunk], capacity: int | None = None, n_total: int | None = None) -> DeviceBatch:
    """Stack per-region chunks into one [R, cap] batch.

    All regions pad to a common capacity and common string widths so the
    stacked arrays are rectangular; `n_total` (>= len(chunks)) additionally
    pads the region axis with empty lanes so R is divisible by the mesh
    size. Delegates to the chunk layer's `to_stacked_device_batch` — ONE
    stacking implementation serves the batch coprocessor, the mesh tier
    and this entry point (host-side np.stack, one HBM transfer per column).
    """
    cap = capacity or max(1, max(c.num_rows() for c in chunks))
    fts = chunks[0].field_types()
    total = n_total or len(chunks)
    padded = list(chunks) + [Chunk.empty(fts) for _ in range(total - len(chunks))]
    return to_stacked_device_batch(padded, cap)


def run_sharded_partial_agg(dag, stacked: DeviceBatch, mesh: Mesh):
    """Scalar-aggregation pushdown over a region-sharded mesh.

    DAG shape: TableScan [Selection] Aggregation(group_by=(), partial=True).
    Each device vmaps the fused per-region program over its local regions,
    then the partial states merge across the mesh (`merge_packed_states`:
    psum for additive states, pmin/pmax for extremes, all_gather for
    bit/first states) — every device ends with the global partial states.

    The per-region pipeline is the builder's own trace (`exec/builder.py`
    build_program(mesh_lanes=...)), not a second hand-rolled interpreter —
    the duplicated scan/selection/agg walk this module used to carry is
    retired onto that shared seam.

    Returns the flat partial-state columns [(value[1], null[1]), ...].
    """
    from dataclasses import replace as _replace

    from ..distsql.planner import mesh_merge_kind
    from ..exec.builder import build_program
    from ..exec.dag import current_schema_fts

    # this entry point always returns EVERY partial-state column — widen
    # the offsets to the full partial schema (callers pass scan-shaped
    # offsets; the merge plan is positional over the state columns)
    n_state = len(current_schema_fts(dag.executors))
    dag = _replace(dag, output_offsets=tuple(range(n_state)))
    # the scalar merge plan is positional over flat [1]-shaped states — a
    # grouped DAG's per-region group tables are NOT key-aligned across
    # lanes and must fail fast, as this entry point always did. String
    # gather states trip the planner gate statically here (the in-trace
    # merge would raise the same NotImplementedError class).
    last = dag.executors[-1]
    from ..exec.dag import Aggregation as _Agg

    assert isinstance(last, _Agg) and not last.group_by, "sharded scalar agg only"
    if mesh_merge_kind(dag) != "scalar":
        raise NotImplementedError(
            "string-valued gather aggregate (first_row/min/max) over the mesh"
        )
    R = int(stacked.row_valid.shape[0])
    cap = int(stacked.row_valid.shape[1])
    prog = build_program(
        dag, (cap,), mesh_lanes=R, mesh_devices=int(mesh.devices.size),
        mesh_kind="scalar",
    )
    merged, _valid, _ex, _ovf, _esc = prog.fn(stacked)
    return [tuple(out) for out in merged]


# --------------------------------------------------------- the merge seam

def partial_merge_plan(aggs) -> list[tuple]:
    """Merge plan per aggregate (the schema in expr/agg.py partial_fts:
    count->[cnt], sum->[sum], avg->[cnt,sum], first_row->[has,val],
    stddev/var->[cnt,sum,sumsq], ...).

    Column entries are ("col", op, unsigned): unsigned BIGINT min/max
    states are raw two's-complement int64 (ops/aggregate.py sign-flip
    trick), so the mesh merge must compare them in the flipped domain too.
    first_row's two state columns merge JOINTLY (value selected by the has
    column) via the ("first_row",) entry consuming both."""
    plan: list[tuple] = []
    for desc in aggs:
        sfts = desc.partial_fts()
        if desc.name in ("count", "sum", "avg", "bit_xor",
                         "stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            # avg states are [count, sum], moment states [count, sum,
            # sumsq] — all additive; bit_xor merge is xor
            op = "sum" if desc.name != "bit_xor" else "xor"
            plan.extend(("col", op, False) for _ in sfts)
        elif desc.name in ("min", "max"):
            plan.extend(("col", desc.name, ft.is_unsigned() and ft.is_int()) for ft in sfts)
        elif desc.name in ("bit_and", "bit_or"):
            plan.extend(("col", "and" if desc.name == "bit_and" else "or", False) for _ in sfts)
        elif desc.name == "first_row":
            plan.append(("first_row",))
        else:
            raise TypeError(f"no mesh merge for aggregate {desc.name!r}")
    return plan


def merge_packed_states(aggs, packed, axis: str = REGION_AXIS) -> list[tuple]:
    """Merge a vmapped partial-agg program's packed outputs across the
    mesh. `packed` is the per-lane output list — one (value[R_local, 1],
    null[R_local, 1]) pair per partial-state column, in `partial_merge_plan`
    order (exactly `exec/builder.py`'s packing for a scalar partial-agg
    DAG). Returns the globally merged [(value[1], null[1]), ...]."""
    plan = partial_merge_plan(aggs)
    merged: list[tuple] = []
    k = 0
    for entry in plan:
        if entry[0] == "first_row":
            has_out, val_out = packed[k], packed[k + 1]
            if len(val_out) != 2 or val_out[0].ndim != 2:
                raise NotImplementedError(
                    "string-valued gather aggregate (first_row/min/max) over the mesh"
                )
            merged.extend(_merge_first_row(
                (has_out[0], has_out[1]), (val_out[0], val_out[1]), axis))
            k += 2
            continue
        _, op, unsigned = entry
        out = packed[k]
        if len(out) != 2 or out[0].ndim != 2:
            raise NotImplementedError(
                "string-valued gather aggregate (first_row/min/max) over the mesh"
            )
        merged.append(_merge_state(op, out[0], out[1], axis, unsigned=unsigned))
        k += 1
    return merged


def _merge_state(op: str, v, nl, axis: str, unsigned: bool = False):
    """Merge one partial-state column across local regions then the mesh.

    v: [R_local, 1] values (NULL lanes zeroed), nl: [R_local, 1] null flags.
    NULL means "no rows seen in this region"; the merged state is NULL only
    if every region's is (ref: aggfuncs partial merge semantics). Sum-like
    states ride psum over ICI (the north-star collective); min/max ride
    pmin/pmax; bit/first states all_gather (tiny) and reduce locally.

    unsigned min/max states hold unsigned values as raw two's-complement
    int64 — compare in the sign-flipped domain (same trick as the kernel).
    """
    allnull = jnp.all(nl, axis=0)
    flip = None
    if unsigned and op in ("min", "max") and jnp.issubdtype(v.dtype, jnp.integer):
        flip = jnp.int64(-0x8000000000000000)
        v = v.astype(jnp.int64) ^ flip
    if op in ("sum", "xor", "or"):
        fill = jnp.zeros((), v.dtype)
    elif op == "and":
        fill = jnp.full((), -1, v.dtype)
    elif op == "min":
        fill = (jnp.full((), jnp.inf, v.dtype) if jnp.issubdtype(v.dtype, jnp.floating)
                else jnp.full((), jnp.iinfo(v.dtype).max, v.dtype))
    elif op == "max":
        fill = (jnp.full((), -jnp.inf, v.dtype) if jnp.issubdtype(v.dtype, jnp.floating)
                else jnp.full((), jnp.iinfo(v.dtype).min, v.dtype))
    else:
        raise AssertionError(op)
    masked = jnp.where(nl, fill, v)

    if op == "sum":
        val = jax.lax.psum(jnp.sum(masked, axis=0), axis)
    elif op == "min":
        val = jax.lax.pmin(jnp.min(masked, axis=0), axis)
    elif op == "max":
        val = jax.lax.pmax(jnp.max(masked, axis=0), axis)
    else:  # xor / or / and: all_gather (tiny) then local bitwise reduce
        red = {"xor": jnp.bitwise_xor, "or": jnp.bitwise_or, "and": jnp.bitwise_and}[op]
        local = red.reduce(masked, axis=0)
        gathered = jax.lax.all_gather(local, axis)  # [D, 1]
        val = red.reduce(gathered, axis=0)
    allnull = jax.lax.pmin(allnull.astype(jnp.int32), axis) > 0
    if flip is not None:
        val = val ^ flip
    if op in ("min", "max"):
        val = jnp.where(allnull, jnp.zeros((), val.dtype), val)
    return val, allnull


def _merge_first_row(has_state, val_state, axis: str):
    """first_row's [has, value] states merge jointly: the first region in
    global region order (device-major — regions were stacked then sharded on
    the leading axis) with has>0 supplies its (value, null) verbatim; NULL
    first values are kept (ref: aggfuncs first_row takes the literal first
    row). Returns the two merged state columns [has, value]."""
    has, _ = has_state
    v, nl = val_state
    ghas = jax.lax.all_gather(has, axis).reshape((-1,) + has.shape[1:])
    gv = jax.lax.all_gather(v, axis).reshape((-1,) + v.shape[1:])
    gn = jax.lax.all_gather(nl, axis).reshape((-1,) + nl.shape[1:])
    present = ghas > 0
    idx = jnp.argmax(present, axis=0)
    any_has = jnp.any(present, axis=0)
    val = jnp.take_along_axis(gv, idx[None], axis=0)[0]
    null = jnp.take_along_axis(gn, idx[None], axis=0)[0]
    val = jnp.where(any_has & ~null, val, jnp.zeros((), v.dtype))
    null = jnp.where(any_has, null, True)
    return [(any_has.astype(jnp.int64), jnp.zeros_like(null)), (val, null)]


def decode_group_mesh_outputs(outs, agg):
    """Shared host-side decode for the grouped shard_map programs
    (grouped.py / joinmesh.py): flat output tuple [group_valid,
    (value, null)*, overflow] with out_specs P(REGION_AXIS) having already
    concatenated the per-device group tables along axis 0. Returns
    (chunk, overflow) in the Complete-mode layout [aggs..., group keys...].
    """
    from ..exec.executor import decode_outputs

    group_valid = np.asarray(outs[0]).reshape(-1)
    overflow = bool(np.asarray(outs[-1]).reshape(-1)[0])
    flat_out = outs[1:-1]
    out_fts = [d.ft for d in agg.aggs] + [g.ft for g in agg.group_by]
    packed = []
    for i, _ft in enumerate(out_fts):
        v = np.asarray(flat_out[2 * i])
        nl = np.asarray(flat_out[2 * i + 1]).reshape(-1)
        packed.append((v, nl))
    return decode_outputs(packed, group_valid, out_fts), overflow


def group_mesh_out_spec(agg):
    """out_specs for the grouped shard_map programs' flat output tuple."""
    from jax.sharding import PartitionSpec as P

    n_out_cols = len(agg.aggs) + len(agg.group_by)
    return tuple([P(REGION_AXIS)] * (1 + 2 * n_out_cols) + [P()])
