"""Mesh data parallelism: regions sharded over TPU devices.

The reference fans per-region cop tasks out to store nodes over gRPC
(ref: copr/coprocessor.go:806 worker pool; batch_coprocessor.go groups
regions per store). The TPU-native shape (SURVEY.md §2.5): stack region
batches on a leading axis, shard that axis over a 1-D `jax.sharding.Mesh`,
run the fused DAG per region under `shard_map` + `vmap`, and psum the
partial aggregate states over ICI — the collective replaces the host-side
merge loop, which is the BASELINE.json north star:

    "per-region partial aggregates are psum-reduced over the ICI mesh
     before final merge"
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..chunk import Chunk, to_device_batch
from ..chunk.device import DeviceBatch, DeviceColumn
from ..exec.dag import Aggregation, DAGRequest
from ..expr.compile import ExprCompiler, normalize_device_column
from ..ops import GatherState, apply_selection, scalar_aggregate

REGION_AXIS = "region"


def region_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (REGION_AXIS,))


def stack_region_batches(chunks: list[Chunk], capacity: int | None = None, n_total: int | None = None) -> DeviceBatch:
    """Stack per-region chunks into one [R, cap] batch.

    All regions pad to a common capacity and common string widths so the
    stacked arrays are rectangular; `n_total` (>= len(chunks)) additionally
    pads the region axis so R is divisible by the mesh size.
    """
    cap = capacity or max(1, max(c.num_rows() for c in chunks))
    # common string width per column
    str_widths: dict[int, int] = {}
    for c in chunks:
        for ci, col in enumerate(c.columns):
            if col.is_varlen():
                w = int((col.offsets[1:] - col.offsets[:-1]).max()) if len(col) else 1
                str_widths[ci] = max(str_widths.get(ci, 1), w)
    batches = [to_device_batch(c, capacity=cap, str_widths=str_widths or None) for c in chunks]
    R = n_total or len(batches)
    while len(batches) < R:
        batches.append(to_device_batch(Chunk.empty(chunks[0].field_types()), capacity=cap, str_widths=str_widths or None))

    def stack(*xs):
        return jnp.stack(xs)

    return jax.tree.map(stack, *batches)


def run_sharded_partial_agg(dag: DAGRequest, stacked: DeviceBatch, mesh: Mesh):
    """Scalar-aggregation pushdown over a region-sharded mesh.

    DAG shape: TableScan [Selection] Aggregation(group_by=(), partial=True).
    Each device: vmap the fused selection over its local regions, reduce the
    partial states across local regions, then psum across the mesh — every
    device ends with the global partial states (the final merge is a single
    host-side finalize).

    Returns list of per-agg state arrays (each [1] after the global merge).
    """
    executors = dag.executors
    agg = executors[-1]
    assert isinstance(agg, Aggregation) and not agg.group_by, "sharded scalar agg only"
    input_fts = [c.ft for c in dag.scan().columns]

    def per_region(cols_and_valid):
        cols, valid = cols_and_valid
        fts = input_fts
        cvals = [normalize_device_column(c) for c in cols]
        for ex in executors[1:-1]:
            comp = ExprCompiler(fts)
            from ..exec.dag import Selection as Sel

            if isinstance(ex, Sel):
                conds = comp.run(list(ex.conditions), cvals)
                valid = apply_selection(valid, conds)
            else:
                raise TypeError(f"sharded pipeline supports scan+selection+agg, got {ex}")
        comp = ExprCompiler(input_fts)
        arg_exprs = [a for desc in agg.aggs for a in desc.args]
        avals = comp.run(arg_exprs, cvals) if arg_exprs else []
        aggs = []
        k = 0
        for desc in agg.aggs:
            aggs.append((desc, avals[k : k + len(desc.args)]))
            k += len(desc.args)
        states, _ovf = scalar_aggregate(aggs, valid, merge=agg.merge)
        # (scalar-path overflow only arises from DISTINCT hash collisions,
        # which the mesh path rejects upstream — _ovf stays False here)
        # flatten to arrays: per agg, per state col: (value[1], null[1]);
        # first_row comes back as a GatherState — materialize its [has,
        # value] wire state here (numeric only on the mesh path)
        flat = []
        for (desc, avs), st in zip(aggs, states):
            if isinstance(st, GatherState):
                vcol = avs[-1]
                if vcol.value.ndim != 1:
                    raise NotImplementedError(
                        f"string-valued gather aggregate {desc.name!r} (first_row/min/max) over the mesh"
                    )
                val = jnp.where(st.has, vcol.value[st.idx], jnp.zeros((), vcol.value.dtype))
                nl = jnp.where(st.has, vcol.null[st.idx], True)
                flat.append((st.has.astype(jnp.int64), jnp.zeros(1, bool)))
                flat.append((val, nl))
            else:
                for v, nl in st:
                    flat.append((v, nl))
        return flat

    # merge plan per aggregate (the schema in expr/agg.py partial_fts:
    # count->[cnt], sum->[sum], avg->[cnt,sum], first_row->[has,val], ...).
    # Column entries are (op, unsigned): unsigned BIGINT min/max states are
    # raw two's-complement int64 (ops/aggregate.py sign-flip trick), so the
    # mesh merge must compare them in the flipped domain too. first_row's
    # two state columns merge JOINTLY (value selected by the has column).
    merge_plan: list[tuple] = []  # ("col", op, unsigned) | ("first_row",)
    for desc in agg.aggs:
        sfts = desc.partial_fts()
        if desc.name in ("count", "sum", "avg", "bit_xor"):
            # avg states are [count, sum] — both additive; bit_xor merge is xor
            op = "sum" if desc.name != "bit_xor" else "xor"
            merge_plan.extend(("col", op, False) for _ in sfts)
        elif desc.name in ("min", "max"):
            merge_plan.extend(("col", desc.name, ft.is_unsigned() and ft.is_int()) for ft in sfts)
        elif desc.name in ("bit_and", "bit_or"):
            merge_plan.extend(("col", "and" if desc.name == "bit_and" else "or", False) for _ in sfts)
        elif desc.name == "first_row":
            merge_plan.append(("first_row",))
        else:
            raise TypeError(f"no mesh merge for aggregate {desc.name!r}")

    def device_fn(local: DeviceBatch):
        # local: [R_local, cap] pytree
        flat = jax.vmap(lambda c, v: per_region((c, v)))(local.cols, local.row_valid)
        merged = []
        k = 0
        for entry in merge_plan:
            if entry[0] == "first_row":
                merged.extend(_merge_first_row(flat[k], flat[k + 1], REGION_AXIS))
                k += 2
            else:
                _, op, unsigned = entry
                v, nl = flat[k]
                merged.append(_merge_state(op, v, nl, REGION_AXIS, unsigned=unsigned))
                k += 1
        return merged

    from .compat import shard_map

    spec_batch = jax.tree.map(lambda _: P(REGION_AXIS), stacked)
    out_spec = [(P(), P())] * _n_state_cols(agg)
    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(spec_batch,),
        out_specs=out_spec,
        # first/bit states merge via all_gather + identical local reduce:
        # replicated in fact, but not statically inferrable by the vma check
        check_vma=False,
    )
    return jax.jit(fn)(stacked)


def _n_state_cols(agg: Aggregation) -> int:
    return sum(len(d.partial_fts()) for d in agg.aggs)


def _merge_state(op: str, v, nl, axis: str, unsigned: bool = False):
    """Merge one partial-state column across local regions then the mesh.

    v: [R_local, 1] values (NULL lanes zeroed), nl: [R_local, 1] null flags.
    NULL means "no rows seen in this region"; the merged state is NULL only
    if every region's is (ref: aggfuncs partial merge semantics). Sum-like
    states ride psum over ICI (the north-star collective); min/max ride
    pmin/pmax; bit/first states all_gather (tiny) and reduce locally.

    unsigned min/max states hold unsigned values as raw two's-complement
    int64 — compare in the sign-flipped domain (same trick as the kernel).
    """
    allnull = jnp.all(nl, axis=0)
    flip = None
    if unsigned and op in ("min", "max") and jnp.issubdtype(v.dtype, jnp.integer):
        flip = jnp.int64(-0x8000000000000000)
        v = v.astype(jnp.int64) ^ flip
    if op in ("sum", "xor", "or"):
        fill = jnp.zeros((), v.dtype)
    elif op == "and":
        fill = jnp.full((), -1, v.dtype)
    elif op == "min":
        fill = (jnp.full((), jnp.inf, v.dtype) if jnp.issubdtype(v.dtype, jnp.floating)
                else jnp.full((), jnp.iinfo(v.dtype).max, v.dtype))
    elif op == "max":
        fill = (jnp.full((), -jnp.inf, v.dtype) if jnp.issubdtype(v.dtype, jnp.floating)
                else jnp.full((), jnp.iinfo(v.dtype).min, v.dtype))
    else:
        raise AssertionError(op)
    masked = jnp.where(nl, fill, v)

    if op == "sum":
        val = jax.lax.psum(jnp.sum(masked, axis=0), axis)
    elif op == "min":
        val = jax.lax.pmin(jnp.min(masked, axis=0), axis)
    elif op == "max":
        val = jax.lax.pmax(jnp.max(masked, axis=0), axis)
    else:  # xor / or / and: all_gather (tiny) then local bitwise reduce
        red = {"xor": jnp.bitwise_xor, "or": jnp.bitwise_or, "and": jnp.bitwise_and}[op]
        local = red.reduce(masked, axis=0)
        gathered = jax.lax.all_gather(local, axis)  # [D, 1]
        val = red.reduce(gathered, axis=0)
    allnull = jax.lax.pmin(allnull.astype(jnp.int32), axis) > 0
    if flip is not None:
        val = val ^ flip
    if op in ("min", "max"):
        val = jnp.where(allnull, jnp.zeros((), val.dtype), val)
    return val, allnull


def _merge_first_row(has_state, val_state, axis: str):
    """first_row's [has, value] states merge jointly: the first region in
    global region order (device-major — regions were stacked then sharded on
    the leading axis) with has>0 supplies its (value, null) verbatim; NULL
    first values are kept (ref: aggfuncs first_row takes the literal first
    row). Returns the two merged state columns [has, value]."""
    has, _ = has_state
    v, nl = val_state
    ghas = jax.lax.all_gather(has, axis).reshape((-1,) + has.shape[1:])
    gv = jax.lax.all_gather(v, axis).reshape((-1,) + v.shape[1:])
    gn = jax.lax.all_gather(nl, axis).reshape((-1,) + nl.shape[1:])
    present = ghas > 0
    idx = jnp.argmax(present, axis=0)
    any_has = jnp.any(present, axis=0)
    val = jnp.take_along_axis(gv, idx[None], axis=0)[0]
    null = jnp.take_along_axis(gn, idx[None], axis=0)[0]
    val = jnp.where(any_has & ~null, val, jnp.zeros((), v.dtype))
    null = jnp.where(any_has, null, True)
    return [(any_has.astype(jnp.int64), jnp.zeros_like(null)), (val, null)]
