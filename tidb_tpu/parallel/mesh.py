"""Mesh data parallelism: regions sharded over TPU devices.

The reference fans per-region cop tasks out to store nodes over gRPC
(ref: copr/coprocessor.go:806 worker pool; batch_coprocessor.go groups
regions per store). The TPU-native shape (SURVEY.md §2.5): stack region
batches on a leading axis, shard that axis over a 1-D `jax.sharding.Mesh`,
run the fused DAG per region under `shard_map` + `vmap`, and psum the
partial aggregate states over ICI — the collective replaces the host-side
merge loop, which is the BASELINE.json north star:

    "per-region partial aggregates are psum-reduced over the ICI mesh
     before final merge"
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..chunk import Chunk, to_device_batch
from ..chunk.device import DeviceBatch, DeviceColumn
from ..exec.dag import Aggregation, DAGRequest
from ..expr.compile import ExprCompiler, normalize_device_column
from ..ops import apply_selection, scalar_aggregate
from ..exec.builder import _agg_out_cols

REGION_AXIS = "region"


def region_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.array(devs[:n]), (REGION_AXIS,))


def stack_region_batches(chunks: list[Chunk], capacity: int | None = None, n_total: int | None = None) -> DeviceBatch:
    """Stack per-region chunks into one [R, cap] batch.

    All regions pad to a common capacity and common string widths so the
    stacked arrays are rectangular; `n_total` (>= len(chunks)) additionally
    pads the region axis so R is divisible by the mesh size.
    """
    cap = capacity or max(1, max(c.num_rows() for c in chunks))
    # common string width per column
    str_widths: dict[int, int] = {}
    for c in chunks:
        for ci, col in enumerate(c.columns):
            if col.is_varlen():
                w = int((col.offsets[1:] - col.offsets[:-1]).max()) if len(col) else 1
                str_widths[ci] = max(str_widths.get(ci, 1), w)
    batches = [to_device_batch(c, capacity=cap, str_widths=str_widths or None) for c in chunks]
    R = n_total or len(batches)
    while len(batches) < R:
        batches.append(to_device_batch(Chunk.empty(chunks[0].field_types()), capacity=cap, str_widths=str_widths or None))

    def stack(*xs):
        return jnp.stack(xs)

    return jax.tree.map(stack, *batches)


def run_sharded_partial_agg(dag: DAGRequest, stacked: DeviceBatch, mesh: Mesh):
    """Scalar-aggregation pushdown over a region-sharded mesh.

    DAG shape: TableScan [Selection] Aggregation(group_by=(), partial=True).
    Each device: vmap the fused selection over its local regions, reduce the
    partial states across local regions, then psum across the mesh — every
    device ends with the global partial states (the final merge is a single
    host-side finalize).

    Returns list of per-agg state arrays (each [1] after the global merge).
    """
    executors = dag.executors
    agg = executors[-1]
    assert isinstance(agg, Aggregation) and not agg.group_by, "sharded scalar agg only"
    input_fts = [c.ft for c in dag.scan().columns]

    def per_region(cols_and_valid):
        cols, valid = cols_and_valid
        fts = input_fts
        cvals = [normalize_device_column(c) for c in cols]
        for ex in executors[1:-1]:
            comp = ExprCompiler(fts)
            from ..exec.dag import Selection as Sel

            if isinstance(ex, Sel):
                conds = comp.run(list(ex.conditions), cvals)
                valid = apply_selection(valid, conds)
            else:
                raise TypeError(f"sharded pipeline supports scan+selection+agg, got {ex}")
        comp = ExprCompiler(input_fts)
        arg_exprs = [a for desc in agg.aggs for a in desc.args]
        avals = comp.run(arg_exprs, cvals) if arg_exprs else []
        aggs = []
        k = 0
        for desc in agg.aggs:
            aggs.append((desc, avals[k : k + len(desc.args)]))
            k += len(desc.args)
        states = scalar_aggregate(aggs, valid, merge=agg.merge)
        # flatten to arrays: per agg, per state col: (value[1], null[1])
        flat = []
        for st in states:
            for v, nl in st:
                flat.append((v, nl))
        return flat

    def device_fn(local: DeviceBatch):
        # local: [R_local, cap] pytree
        flat = jax.vmap(lambda c, v: per_region((c, v)))(local.cols, local.row_valid)
        merged = []
        for v, nl in flat:
            # v: [R_local, 1]; merge across local regions then across mesh.
            # Sum-merge is correct for count/sum states; NULL means "no rows
            # seen" so the merged null = all-null (and its value lanes are 0).
            allnull = jnp.all(nl, axis=0)
            val = jnp.sum(jnp.where(nl, jnp.zeros((), v.dtype), v), axis=0)
            val = jax.lax.psum(val, REGION_AXIS)
            allnull = jax.lax.pmin(allnull.astype(jnp.int32), REGION_AXIS) > 0
            merged.append((val, allnull))
        return merged

    from jax import shard_map

    spec_batch = jax.tree.map(lambda _: P(REGION_AXIS), stacked)
    out_spec = [(P(), P())] * _n_state_cols(agg)
    fn = shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(spec_batch,),
        out_specs=out_spec,
    )
    return jax.jit(fn)(stacked)


def _n_state_cols(agg: Aggregation) -> int:
    return sum(len(d.partial_fts()) for d in agg.aggs)
