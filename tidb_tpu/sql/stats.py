"""Table/column statistics: equi-depth histograms + TopN + NDV, built by
ANALYZE and consumed by the planner's cardinality estimates
(ref: pkg/statistics — histogram.go equi-depth buckets, cmsketch.go TopN,
builder.go BuildColumn; store-side collection cophandler/analyze.go).

The reference samples on the store side and sketches NDV with FMSketch;
in-process the full column is available, so NDV and TopN are exact and the
histogram is built from one sorted pass. The *consumer* contract matches:
  est_rows(column, intervals) -> estimated matching rows
with TopN answering point hits exactly, buckets interpolating ranges."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..expr.eval_ref import compare
from ..types import Datum, DatumKind
from .ranger import Interval

DEFAULT_BUCKETS = 64
DEFAULT_TOPN = 16
CM_DEPTH = 5
CM_WIDTH = 2048


class CMSketch:
    """Count-Min sketch over datum group keys (ref: pkg/statistics/
    cmsketch.go — d x w counters, point frequency = min over rows; TopN
    values are kept OUT of the sketch, exactly like the reference splits
    CMSketchAndTopN)."""

    __slots__ = ("depth", "width", "rows")

    def __init__(self, depth: int = CM_DEPTH, width: int = CM_WIDTH):
        self.depth = depth
        self.width = width
        self.rows = [[0] * width for _ in range(depth)]

    @staticmethod
    def _key(d: Datum):
        from ..exec.executor import datum_group_key

        return datum_group_key(d)

    def insert(self, d: Datum, count: int = 1):
        k = hash(self._key(d))
        for i in range(self.depth):
            h = hash((i * 0x9E3779B97F4A7C15, k)) % self.width
            self.rows[i][h] += count

    def query(self, d: Datum) -> int:
        k = hash(self._key(d))
        return min(
            self.rows[i][hash((i * 0x9E3779B97F4A7C15, k)) % self.width]
            for i in range(self.depth)
        )


@dataclass
class Bucket:
    """(ref: statistics.Bucket — lower/upper inclusive, cumulative count)."""

    lower: Datum
    upper: Datum
    count: int  # rows in this bucket (not cumulative)
    repeats: int  # occurrences of `upper`
    ndv: int  # distinct values in the bucket


@dataclass
class ColumnStats:
    null_count: int = 0
    ndv: int = 0
    total: int = 0  # non-null rows
    topn: list = field(default_factory=list)  # [(Datum, count)] most frequent
    buckets: list = field(default_factory=list)  # [Bucket] ascending
    cmsketch: CMSketch | None = None  # point frequencies for non-TopN values


@dataclass
class TableStats:
    row_count: int = 0
    version: int = 0  # TSO at ANALYZE time
    columns: dict = field(default_factory=dict)  # col name -> ColumnStats


def build_column_stats(values: list, n_buckets: int = DEFAULT_BUCKETS,
                       n_topn: int = DEFAULT_TOPN) -> ColumnStats:
    """One sorted pass over the column's datums (ref: builder.go
    BuildColumnHist + the TopN extraction in cmsketch.go)."""
    import functools

    nonnull = [d for d in values if not d.is_null()]
    cs = ColumnStats(null_count=len(values) - len(nonnull), total=len(nonnull))
    if not nonnull:
        return cs
    nonnull.sort(key=functools.cmp_to_key(compare))
    groups: list[tuple[Datum, int]] = []
    for d in nonnull:
        if groups and compare(groups[-1][0], d) == 0:
            groups[-1] = (groups[-1][0], groups[-1][1] + 1)
        else:
            groups.append((d, 1))
    cs.ndv = len(groups)
    # TopN: most frequent values that repeat (point queries answer exactly)
    frequent = sorted((g for g in groups if g[1] > 1), key=lambda g: -g[1])[:n_topn]
    topn_vals = {id(g[0]) for g in frequent}
    cs.topn = [(d, c) for d, c in frequent]
    rest = [g for g in groups if id(g[0]) not in topn_vals]
    if not rest:
        return cs
    cs.cmsketch = CMSketch()
    for d, c in rest:
        cs.cmsketch.insert(d, c)
    depth = max(sum(c for _, c in rest) // n_buckets + 1, 1)
    cur: Bucket | None = None
    for d, c in rest:
        if cur is None or cur.count >= depth:
            cur = Bucket(lower=d, upper=d, count=c, repeats=c, ndv=1)
            cs.buckets.append(cur)
        else:
            cur.upper, cur.repeats = d, c
            cur.count += c
            cur.ndv += 1
    return cs


def _as_float(d: Datum) -> float | None:
    from ..types import MyDecimal, MyTime

    if d.kind in (DatumKind.Int64, DatumKind.Uint64):
        return float(d.val)
    if d.kind in (DatumKind.Float32, DatumKind.Float64):
        return float(d.val)
    if d.kind == DatumKind.MysqlDecimal:
        return d.val.to_float()
    if d.kind == DatumKind.MysqlTime:
        return float(d.val.to_packed())
    return None


def _in_interval(d: Datum, iv: Interval) -> bool:
    if iv.low is not None:
        c = compare(d, iv.low)
        if c < 0 or (c == 0 and not iv.low_inc):
            return False
    if iv.high is not None:
        c = compare(d, iv.high)
        if c > 0 or (c == 0 and not iv.high_inc):
            return False
    return True


def est_interval_rows(cs: ColumnStats, iv: Interval) -> float:
    """Estimated rows matching one interval (ref: histogram.go
    BetweenRowCount/equalRowCount + TopN adjustments)."""
    hit = sum(c for d, c in cs.topn if _in_interval(d, iv))
    is_point = (
        iv.low is not None and iv.high is not None
        and iv.low_inc and iv.high_inc and compare(iv.low, iv.high) == 0
    )
    if is_point:
        if any(compare(d, iv.low) == 0 for d, _ in cs.topn):
            return hit  # TopN answers exactly; buckets exclude TopN values
        # equality not answered by TopN: the CM sketch answers point
        # frequency (ref: cmsketch.go queryValue); the bucket average is
        # the no-sketch fallback (histogram.go equalRowCount)
        if cs.cmsketch is not None:
            return hit + cs.cmsketch.query(iv.low)
        for b in cs.buckets:
            if compare(iv.low, b.lower) >= 0 and compare(iv.low, b.upper) <= 0:
                if compare(iv.low, b.upper) == 0:
                    return hit + b.repeats
                return hit + b.count / max(b.ndv, 1)
        return hit
    for b in cs.buckets:
        lo_in = iv.low is None or compare(b.lower, iv.low) >= 0
        hi_in = iv.high is None or compare(b.upper, iv.high) <= 0
        if lo_in and hi_in:
            # entire bucket inside (ignoring open-endpoint slivers)
            hit += b.count
            continue
        # bucket straddles a boundary: linear interpolation on numerics,
        # half-bucket otherwise (the reference's out-of-range heuristic)
        blo, bhi = _as_float(b.lower), _as_float(b.upper)
        if blo is None or bhi is None or bhi <= blo:
            overlap_lo = iv.low is not None and _in_interval(b.upper, iv)
            overlap_hi = iv.high is not None and _in_interval(b.lower, iv)
            if overlap_lo or overlap_hi:
                hit += b.count / 2
            continue
        flo = None if iv.low is None else _as_float(iv.low)
        fhi = None if iv.high is None else _as_float(iv.high)
        lo = blo if flo is None else flo
        hi = bhi if fhi is None else fhi
        lo, hi = max(lo, blo), min(hi, bhi)
        if hi >= lo:
            hit += b.count * (hi - lo) / (bhi - blo)
    return hit


def est_selectivity(cs: ColumnStats, intervals: list) -> float:
    """Selectivity of a union of disjoint intervals over one column."""
    if cs.total + cs.null_count == 0:
        return 1.0
    rows = sum(est_interval_rows(cs, iv) for iv in intervals)
    return min(max(rows / max(cs.total + cs.null_count, 1), 0.0), 1.0)
