"""Session: SQL strings in, rows out — the engine's
`session.ExecuteStmt` (ref: pkg/session/session.go:2008) collapsed to the
single-process shape: parse -> subquery rewrite -> plan -> execute_root
over the embedded TPU store, with real Percolator transactions.

Statement coverage: CREATE/DROP/ALTER/RENAME TABLE, CREATE/DROP INDEX,
INSERT (VALUES / SELECT / REPLACE / IGNORE), UPDATE, DELETE, TRUNCATE,
SELECT (joins, aggregation, window functions, subqueries, CTEs incl.
recursive, UNION, HAVING, ORDER/LIMIT, DISTINCT, FOR UPDATE, point-get
fast path), BEGIN/COMMIT/ROLLBACK (pessimistic + optimistic 2PC),
PREPARE/EXECUTE/DEALLOCATE, CREATE/DROP USER, GRANT/REVOKE, ANALYZE,
LOAD DATA, BACKUP/RESTORE, ADMIN SHOW DDL JOBS / CHECK TABLE, SET/SHOW,
EXPLAIN. Everything else raises loudly rather than silently no-op."""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import topsql
from ..chunk import Chunk
from ..codec import tablecodec
from ..codec.rowcodec import fill_origin_default
from ..distsql import execute_root, full_table_ranges
from ..exec.dag import ColumnInfo, DAGRequest, Selection, TableScan
from ..expr.eval_ref import RefEvaluator, _truth
from ..expr.ir import col
from ..parser import ast as A
from ..parser.parser import parse_one
from ..store import QuorumLostError, TPUStore
from ..types import Datum, DatumKind, FieldType, MyDecimal, MyTime, new_longlong
from .catalog import Catalog, CatalogError, TableMeta
from .planner import PlanError, _Lowerer, _Scope, _TableRef, _coerce_datum, plan_select

HANDLE_FT = new_longlong(notnull=True)


@dataclass
class TxnState:
    """One open transaction (ref: session's LazyTxn + the client-side
    memdb buffer; pkg/store/driver/txn). Mutations buffer at the KV level
    (what 2PC ships); row_ops keep the row-level overlay SELECTs need for
    read-your-writes (the UnionScan analog, pkg/executor/union_scan.go)."""

    start_ts: int
    mode: str  # "optimistic" | "pessimistic"
    explicit: bool
    mutations: dict = field(default_factory=dict)  # key -> bytes | None
    row_ops: dict = field(default_factory=dict)  # table_id -> {handle: [Datum] | None}
    locked: set = field(default_factory=set)  # pessimistic-locked keys
    row_delta: dict = field(default_factory=dict)  # table_id -> row-count delta
    # (applied to catalog stats only on successful commit)
    index_muts: dict = field(default_factory=dict)  # index-key subset of mutations
    named_savepoints: dict = field(default_factory=dict)  # SAVEPOINT name -> snapshot
    schema_ver: int = -1  # catalog version at txn start (DDL fencing)

    def savepoint(self):
        """Statement-level snapshot: a failed statement inside an explicit
        txn must leave no partial buffer (MySQL implicit statement
        savepoint; ref: session.StmtRollback)."""
        return (
            dict(self.mutations),
            {tid: dict(ops) for tid, ops in self.row_ops.items()},
            set(self.locked),
            dict(self.row_delta),
            dict(self.index_muts),
        )

    def restore(self, sp):
        self.mutations, self.row_ops, self.locked, self.row_delta, self.index_muts = (
            dict(sp[0]),
            {tid: dict(ops) for tid, ops in sp[1].items()},
            set(sp[2]),
            dict(sp[3]),
            dict(sp[4]),
        )


@dataclass
class Result:
    """(ref: the server's result set; rows are Datum lists)."""

    columns: list = field(default_factory=list)
    rows: list = field(default_factory=list)
    affected: int = 0
    fts: list | None = None  # column FieldTypes (wire column definitions)

    def scalar(self):
        return self.rows[0][0].val if self.rows else None

    def values(self):
        return [[d.val if not d.is_null() else None for d in r] for r in self.rows]


def qualify_tables_ast(stmt, cur_db: str) -> None:
    """Database-qualified name resolution: every A.TableName in the
    statement folds its database into the catalog key ("db.table"), and
    unqualified names under a non-default current database get the same
    prefix — the single-namespace catalog then serves multiple databases
    transparently (ref: the schema-qualified resolution in
    pkg/planner/core/logical_plan_builder.go buildDataSource). CTE names
    (any nesting level) stay raw; under the virtual schemas the db FIELD
    is set instead so _bind_information_schema still recognizes them.
    Also used by view expansion (subquery.py) with the view's defining
    database."""
    cte_names: set = set()

    def collect_ctes(n):
        if isinstance(n, (list, tuple)):
            for x in n:
                collect_ctes(x)
            return
        if not hasattr(n, "__dataclass_fields__"):
            return
        for cte in getattr(n, "ctes", None) or []:
            cte_names.add(cte.name.lower())
        for f_ in n.__dataclass_fields__:
            collect_ctes(getattr(n, f_))

    collect_ctes(stmt)
    cte_names.add("dual")  # FROM DUAL: pseudo-table, never db-qualified
    virtual = ("information_schema", "performance_schema")

    def walk(n):
        if isinstance(n, (list, tuple)):
            for x in n:
                walk(x)
            return
        if not hasattr(n, "__dataclass_fields__"):
            return
        if isinstance(n, A.SelectStmt) and isinstance(n.from_clause, A.TableName) \
                and not (n.from_clause.db or "") \
                and n.from_clause.name.lower() == "dual":
            # FROM DUAL is the no-table SELECT (MySQL compat; ref:
            # parser.y TableRefsClause DUAL production)
            n.from_clause = None
        if isinstance(n, A.TableName):
            db = (n.db or "").lower()
            if db in virtual:
                return
            nm = n.name.lower()
            if "." in nm:
                return  # already a qualified catalog key (idempotent)
            if db and db != "test":
                n.name = f"{db}.{nm}"
                n.db = ""
            elif not db and cur_db in virtual and nm not in cte_names:
                n.db = cur_db
            elif not db and cur_db != "test" and nm not in cte_names:
                n.name = f"{cur_db}.{nm}"
            return
        for f_ in n.__dataclass_fields__:
            walk(getattr(n, f_))

    walk(stmt)


def ast_digest(stmt) -> str:
    """Literal-masked structural digest of a statement AST (ref: the
    normalized-SQL digest pkg/parser/digester.go feeds to bindinfo and
    Top SQL): constants become '?', identifiers keep case-folded names,
    hints are EXCLUDED so a hinted statement digests equal to its
    original."""
    import hashlib

    parts: list = []

    def walk(n):
        if isinstance(n, (list, tuple)):
            for x in n:
                walk(x)
            return
        if isinstance(n, A.Literal):
            parts.append("?")
            return
        if isinstance(n, A.ParamMarker):
            parts.append("?")
            return
        if not hasattr(n, "__dataclass_fields__"):
            if isinstance(n, str):
                parts.append(n.lower())
            elif n is not None:
                parts.append(str(n))
            return
        parts.append(type(n).__name__)
        for f_ in n.__dataclass_fields__:
            if f_ == "hints":
                continue
            walk(getattr(n, f_))

    walk(stmt)
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:32]


def _sql_str_escape(s: str) -> str:
    """Escape a value for embedding in a single-quoted SQL literal.

    The lexer honors BOTH backslash escapes and doubled quotes
    (parser/lexer.py), so doubling quotes alone is not enough: a value
    ending in a lone backslash would swallow the closing quote and break
    out of the literal (ADVICE r5 low — the CREATE/DROP USER mirror SQL).
    Backslashes must double FIRST, then quotes."""
    return s.replace("\\", "\\\\").replace("'", "''")


class SQLError(ValueError):
    """User-facing statement error. `code` is the MySQL error number the
    wire server puts in the ERR packet (ref: pkg/errno; 1105 = generic
    ER_UNKNOWN_ERROR, 9005 = ErrRegionUnavailable, 3024 = ER_QUERY_TIMEOUT,
    1317 = ER_QUERY_INTERRUPTED)."""

    def __init__(self, message: str, code: int = 1105):
        super().__init__(message)
        self.code = code


def _show_like(stmt, name: str) -> bool:
    """SHOW ... LIKE 'pattern' filter (MySQL LIKE: % any run, _ one char,
    case-insensitive on identifier-ish names)."""
    pat = getattr(stmt, "pattern", None)
    if not pat:
        return True
    import re

    rx = []
    i = 0
    while i < len(pat):
        c = pat[i]
        if c == "\\" and i + 1 < len(pat):
            # MySQL LIKE escape: \% \_ \\ match the literal character
            rx.append(re.escape(pat[i + 1]))
            i += 2
            continue
        rx.append(".*" if c == "%" else "." if c == "_" else re.escape(c))
        i += 1
    return re.fullmatch("".join(rx), name, re.I) is not None


def _referenced_tables(stmt) -> set:
    """Table names referenced anywhere in a statement (conservative walk:
    CTE names that shadow real catalog tables still show up and still get
    checked — the CTE body may read the real table; names that match no
    catalog table are skipped by the caller)."""
    names: set = set()

    def walk(n):
        if isinstance(n, A.TableName):
            if n.db.lower() != "information_schema":
                names.add(n.name.lower())
            return
        if not hasattr(n, "__dataclass_fields__"):
            return
        for f_ in n.__dataclass_fields__:
            v = getattr(n, f_)
            for it in v if isinstance(v, (list, tuple)) else [v]:
                if isinstance(it, tuple):
                    for x in it:
                        if hasattr(x, "__dataclass_fields__"):
                            walk(x)
                elif hasattr(it, "__dataclass_fields__"):
                    walk(it)

    walk(stmt)
    return names


class Session:
    """One client session over an embedded store. Multiple sessions may
    share a store+catalog (pass them in) — the testkit pattern
    (ref: pkg/testkit TestKit over a shared mockstore)."""

    def __init__(self, store: TPUStore | None = None, catalog: Catalog | None = None, config=None):
        from ..config import Config
        from . import builtins_host
        from .sysvar import SysVarStore

        # module-level because extension builtins receive plain values; a
        # fresh session must not inherit a previous session's SET
        builtins_host.BLOCK_ENCRYPTION_MODE = "aes-128-ecb"
        self.store = store or TPUStore()
        if catalog is None and store is not None:
            # reopening an existing store: recover the schema from the
            # m-prefix keyspace (ref: domain.go:1131 infoschema reload)
            from .meta import load_catalog

            catalog = load_catalog(store)
        self.catalog = catalog or Catalog()
        self.txn: TxnState | None = None
        self.sysvars = SysVarStore()
        self.user_vars: dict[str, object] = {}
        self.user = "root"  # authenticated user (the server sets this)
        self.db = "test"  # current database (USE switches; catalog keys
        # for non-default databases are "db.table")
        self._bootstrap_mysql_schema()
        self.prepared: dict[str, object] = {}  # PREPARE name -> template record
        self._explain_sink: list | None = None  # EXPLAIN ANALYZE summaries
        # --- production front door (ISSUE 15) -------------------------
        self._stmt_probe = None  # plan-cache probe for the current top stmt
        self._last_sql = ""  # raw text of the current top statement
        self._last_plan_cache = None  # (status, reason, tier) of last consult
        self._record_digest = None  # (norm, digest) the stmt log records under
        self._bindings_rev = 0  # session-binding revision (plan-cache key part)
        # --- cross-session fused execution (ISSUE 19) -----------------
        self._coalesce_hint = False  # set around plan-cache-hit point gets
        self._text_serve_type = "select"  # stmt kind of the last text-serve hit
        if config is not None:
            # instance config seeds session sysvars (ref: setGlobalVars
            # bridging config -> sysvar defaults, cmd/tidb-server/main.go:654)
            self.sysvars.set("tidb_distsql_scan_concurrency", str(config.distsql_scan_concurrency))
            self.sysvars.set("tidb_mem_quota_query", str(config.mem_quota_query))
            self.sysvars.set("tidb_mem_quota_session", str(config.mem_quota_session))
            # admission control onto the store's gate (ISSUE 15)
            gate = getattr(self.store, "admission", None)
            if gate is not None:
                gate.configure(
                    max_inflight=config.admission_max_inflight,
                    session_queue=config.admission_session_queue,
                    queue_wait_ms=config.admission_queue_wait_ms,
                    shed_backoff_ms=config.admission_shed_backoff_ms,
                    max_dispatch=config.admission_max_dispatch,
                    cost_classed=config.admission_cost_classed,
                )
            if config.paging_size:
                self.sysvars.set("tidb_enable_paging", "ON")
                self.sysvars.set("tidb_max_chunk_size", str(config.paging_size))
            # cross-session fused execution (ISSUE 19)
            if config.coalesce_enabled:
                self.sysvars.set("tidb_tpu_enable_coalesce", "ON")
            self.sysvars.set("tidb_tpu_coalesce_wait_us", str(config.coalesce_wait_us))
            self.sysvars.set("tidb_tpu_coalesce_max_lanes", str(config.coalesce_max_lanes))
            # PD scheduling knobs onto the store's placement driver
            pd = getattr(self.store, "pd", None)
            if pd is not None:
                pd.conf.tick_interval = config.pd_tick_interval
                pd.conf.max_region_size = config.pd_max_region_size
                pd.conf.max_region_keys = config.pd_max_region_keys

    # the writable slice of the mysql schema (ref: session/bootstrap.go:768
    # doDDLWorks — the full bootstrap creates ~40 tables; these are the
    # ones DML actually targets: pushdown/optimizer blacklists, bindings,
    # stats metadata, GC state)
    _MYSQL_BOOTSTRAP = [
        "CREATE TABLE IF NOT EXISTS `mysql.expr_pushdown_blacklist` (name VARCHAR(100) NOT NULL, store_type VARCHAR(100) NOT NULL DEFAULT 'tikv,tiflash,tidb', reason VARCHAR(200))",
        "CREATE TABLE IF NOT EXISTS `mysql.opt_rule_blacklist` (name VARCHAR(100) NOT NULL)",
        "CREATE TABLE IF NOT EXISTS `mysql.bind_info` (original_sql TEXT, bind_sql TEXT, default_db TEXT, status TEXT, create_time DATETIME, update_time DATETIME, charset TEXT, collation TEXT, source VARCHAR(10), sql_digest VARCHAR(64), plan_digest VARCHAR(64))",
        "CREATE TABLE IF NOT EXISTS `mysql.stats_meta` (version BIGINT NOT NULL, table_id BIGINT NOT NULL, modify_count BIGINT NOT NULL DEFAULT 0, count BIGINT NOT NULL DEFAULT 0, snapshot BIGINT NOT NULL DEFAULT 0)",
        "CREATE TABLE IF NOT EXISTS `mysql.tidb` (variable_name VARCHAR(64) NOT NULL, variable_value VARCHAR(1024) DEFAULT NULL, comment VARCHAR(1024))",
        "CREATE TABLE IF NOT EXISTS `mysql.global_variables` (variable_name VARCHAR(64) NOT NULL, variable_value VARCHAR(16383) DEFAULT NULL)",
        # account tables (ref: bootstrap.go CreateUserTable/CreateDBPrivTable
        # and friends); CREATE USER/GRANT mirror rows in via privilege.py
        "CREATE TABLE IF NOT EXISTS `mysql.user` (Host CHAR(255), User CHAR(32), authentication_string TEXT, plugin CHAR(64), Select_priv CHAR(1) DEFAULT 'N', Insert_priv CHAR(1) DEFAULT 'N', Update_priv CHAR(1) DEFAULT 'N', Delete_priv CHAR(1) DEFAULT 'N', Create_priv CHAR(1) DEFAULT 'N', Drop_priv CHAR(1) DEFAULT 'N', Grant_priv CHAR(1) DEFAULT 'N', Super_priv CHAR(1) DEFAULT 'N', account_locked CHAR(1) DEFAULT 'N')",
        "CREATE TABLE IF NOT EXISTS `mysql.db` (Host CHAR(255), DB CHAR(64), User CHAR(32), Select_priv CHAR(1) DEFAULT 'N', Insert_priv CHAR(1) DEFAULT 'N', Update_priv CHAR(1) DEFAULT 'N', Delete_priv CHAR(1) DEFAULT 'N', Create_priv CHAR(1) DEFAULT 'N', Drop_priv CHAR(1) DEFAULT 'N')",
        "CREATE TABLE IF NOT EXISTS `mysql.tables_priv` (Host CHAR(255), DB CHAR(64), User CHAR(32), Table_name CHAR(64), Grantor CHAR(128), Table_priv TEXT, Column_priv TEXT)",
        "CREATE TABLE IF NOT EXISTS `mysql.gc_delete_range` (job_id BIGINT NOT NULL, element_id BIGINT NOT NULL, start_key VARCHAR(255), end_key VARCHAR(255), ts BIGINT)",
        "CREATE TABLE IF NOT EXISTS `mysql.analyze_jobs` (id BIGINT, table_schema CHAR(64), table_name CHAR(64), job_info TEXT, start_time DATETIME, end_time DATETIME, state VARCHAR(15))",
        "CREATE TABLE IF NOT EXISTS `mysql.stats_histograms` (table_id BIGINT NOT NULL, is_index TINYINT NOT NULL, hist_id BIGINT NOT NULL, distinct_count BIGINT NOT NULL, null_count BIGINT DEFAULT 0, version BIGINT DEFAULT 0)",
        "CREATE TABLE IF NOT EXISTS `mysql.stats_buckets` (table_id BIGINT NOT NULL, is_index TINYINT NOT NULL, hist_id BIGINT NOT NULL, bucket_id BIGINT NOT NULL, count BIGINT NOT NULL, repeats BIGINT NOT NULL, upper_bound TEXT, lower_bound TEXT)",
    ]

    def _bootstrap_mysql_schema(self) -> None:
        if getattr(self.catalog, "_mysql_bootstrapped", False):
            return
        self.catalog._mysql_bootstrapped = True
        for ddl in self._MYSQL_BOOTSTRAP:
            try:
                self.execute_stmt(parse_one(ddl))
            except Exception:  # noqa: BLE001 — one bad table must not
                pass  # block login or the remaining bootstrap tables

    # ------------------------------------------------ plan bindings
    def _binding(self, stmt: A.BindingStmt) -> Result:
        """CREATE/DROP [GLOBAL|SESSION] BINDING (ref: pkg/bindinfo
        binding.go; match-at-optimize pkg/planner/optimize.go:135). The
        digest is literal-masked and structural — the same statement shape
        with different constants matches, like the reference's normalized
        SQL digest."""
        digest = ast_digest(stmt.target)
        store = self.catalog.bindings if stmt.scope == "global" else self._session_bindings()
        if stmt.action == "drop":
            store.pop(digest, None)
            # binding changes re-key/invalidate cached plans (ISSUE 15)
            if stmt.scope == "global":
                self.catalog.bindings_rev += 1
            else:
                self._bindings_rev += 1
            if stmt.scope == "global":
                try:
                    self.execute(
                        "delete from mysql.bind_info where sql_digest = "
                        f"'{digest}'"
                    )
                except SQLError:
                    pass
            return Result()
        if type(stmt.hinted) is not type(stmt.target):
            raise SQLError("binding: the USING statement must match the bound statement's type")
        if ast_digest(stmt.hinted) != digest:
            raise SQLError("binding: the USING statement differs structurally from the bound one")
        store[digest] = {
            "original": stmt.target_sql, "bind": stmt.hinted_sql,
            "ast": stmt.hinted, "scope": stmt.scope, "db": self.db,
        }
        if stmt.scope == "global":
            self.catalog.bindings_rev += 1
        else:
            self._bindings_rev += 1
        if stmt.scope == "global":
            try:
                # same escape contract as the user mirror: backslashes
                # must double BEFORE quotes or a trailing \ breaks out of
                # the literal and the binding silently fails to mirror
                o = _sql_str_escape(stmt.target_sql)
                b = _sql_str_escape(stmt.hinted_sql)
                self.execute(
                    "insert into mysql.bind_info (original_sql, bind_sql, default_db, "
                    f"status, source, sql_digest) values ('{o}', '{b}', '{self.db}', "
                    f"'enabled', 'manual', '{digest}')"
                )
            except SQLError:
                pass
        return Result()

    def _session_bindings(self) -> dict:
        if not hasattr(self, "_bindings"):
            self._bindings = {}
        return self._bindings

    def _match_binding(self, stmt):
        """Graft a matching binding's HINTS onto the incoming statement —
        never its literals: the digest is literal-masked, so the incoming
        query keeps its own constants and only the optimizer directives
        transfer (ref: bindinfo BindSQL = normalized SQL + hint set).
        Returns the (mutated) statement or None."""
        if not isinstance(stmt, A.SelectStmt):
            return None
        digest = ast_digest(stmt)
        rec = self._session_bindings().get(digest) or self.catalog.bindings.get(digest)
        if rec is None or not isinstance(rec["ast"], A.SelectStmt):
            return None
        stmt.hints = list(rec["ast"].hints)
        return stmt

    def _runaway_checker(self):
        """Per-statement RunawayChecker from max_execution_time (ms, 0 =
        unlimited) — the BeforeCopRequest hook the dispatch loop consults
        (ref: resourcegroup/runaway checker.go:27). Stored on the session
        so KILL QUERY from another session can flip its kill flag."""
        from ..distsql.runaway import RunawayChecker

        c = RunawayChecker(self.sysvars.get_int("max_execution_time"))
        self._active_checker = c
        return c

    def kill_query(self):
        """KILL QUERY analog: abort the statement at its next dispatch
        boundary (ref: server kill handling -> sessVars.Killed)."""
        c = getattr(self, "_active_checker", None)
        if c is not None:
            c.kill()

    def _next_ts(self) -> int:
        return self.store.next_ts()

    def _read_ts(self) -> int:
        """Snapshot ts: the open txn's start_ts (repeatable read), else
        the tidb_snapshot stale-read ts when set (ref: sessiontxn/staleread
        — reads rewind to a historical version), else a fresh TSO tick."""
        if self.txn is not None:
            return self.txn.start_ts
        snap = self.sysvars.get("tidb_snapshot")
        if snap:
            ts = int(snap)
            if ts <= getattr(self.store, "gc_safepoint", -1):
                # ref: TiDB "snapshot is older than GC safe point" — GC may
                # have collected the versions this read would need
                raise SQLError(
                    f"snapshot {ts} is older than GC safe point {self.store.gc_safepoint}"
                )
            return ts
        return self.store.next_ts()

    def _read_engines(self) -> tuple:
        """tidb_isolation_read_engines as a normalized tuple (the sysvar
        validator already rejected unknown names and folded the reference
        aliases). In-transaction reads and EXPLAIN ANALYZE runs strip the
        columnar replica: a txn must see its own snapshot/buffer on the
        authoritative row store, and ANALYZE wants the per-region summary
        attribution only the cop path produces (ref: TiDB routing
        in-transaction reads to TiKV regardless of the engine list)."""
        engines = tuple(self.sysvars.get("tidb_isolation_read_engines").split(","))
        if self.txn is not None or self._explain_sink is not None:
            engines = tuple(e for e in engines if e != "columnar") or ("tpu",)
        return engines

    def _pin_read_ts(self) -> int:
        """_read_ts, registered against GC for the statement's duration so a
        background run_gc tick cannot collect the version this read is
        looking at mid-statement (ref: gc_worker.go
        calcSafePointByMinStartTS — the safepoint honors every active
        operation, not only explicit txns). Pair with _unpin_read_ts."""
        ts = self._read_ts()
        if self.txn is None:
            self.store.register_snapshot(ts)
        return ts

    def _unpin_read_ts(self, ts: int) -> None:
        if self.txn is None or self.txn.start_ts != ts:
            self.store.unregister_snapshot(ts)

    # ---------------------------------------------------------------- txn
    def _begin(self, explicit: bool = True):
        if self.sysvars.get("tidb_snapshot"):
            # ref: TiDB rejects BEGIN in stale-read mode rather than let a
            # fresh txn ts silently override the historical snapshot
            raise SQLError("can not execute BEGIN when 'tidb_snapshot' is set")
        self.txn = TxnState(
            start_ts=self.store.next_ts(),
            mode=self.sysvars.get("tidb_txn_mode") or "pessimistic",
            explicit=explicit,
            schema_ver=self.catalog.version,
        )
        from ..util import metrics

        metrics.OPEN_TXNS.inc()
        # pin the snapshot against GC for the txn's lifetime
        self.store.register_snapshot(self.txn.start_ts)

    def _commit(self):
        from ..store.txn import TxnError

        txn, self.txn = self.txn, None
        if txn is None:
            return
        from ..util import metrics

        metrics.OPEN_TXNS.dec()
        self.store.unregister_snapshot(txn.start_ts)
        if not txn.mutations:
            self.store.txn.release_all(txn.start_ts)
            return
        if txn.schema_ver != self.catalog.version:
            # concurrent DDL: buffered mutations were computed against an
            # older schema (e.g. without a newly-built index) — committing
            # would corrupt it (ref: TiDB "Information schema is changed")
            self.store.txn.release_all(txn.start_ts)
            raise SQLError(
                "Information schema is changed during the execution of the statement "
                "(schema version moved from "
                f"{txn.schema_ver} to {self.catalog.version}) — transaction aborted"
            )
        try:
            # commit_ts is allocated INSIDE the engine's critical section:
            # TSO monotonicity then guarantees no reader can hold a
            # read_ts >= commit_ts before the apply completes
            if self._coalesce_commit(txn) is None:
                self.store.txn.commit_txn(txn.mutations, txn.start_ts, self.store.next_ts)
        except TxnError as exc:
            self.store.txn.release_all(txn.start_ts)
            raise SQLError(str(exc)) from exc
        except QuorumLostError:
            # a quorum-lost region refused the commit before anything
            # applied: drop the locks and let execute() map it to 9005
            self.store.txn.release_all(txn.start_ts)
            raise
        # non-mutated pessimistic locks (SELECT FOR UPDATE) release now
        self.store.txn.release_all(txn.start_ts)
        # planner row-count stats apply only once the txn is durable
        for tid, delta in txn.row_delta.items():
            meta = self.catalog.table_by_id(tid)
            if meta is not None:
                meta.row_count = max(meta.row_count + delta, 0)

    def _coalesce_commit(self, txn):
        """Group-commit window for autocommit single-statement writes
        (ISSUE 19): park the mutations in the store's coalescer so
        concurrent sessions' commits ship as ONE quorum proposal per
        (region, window), each lane at its own commit ts. Returns the
        commit_ts, or None when this commit must take (or fell back to)
        the canonical single path — a conflict inside the window releases
        the lane's locks, so retrying via commit_txn re-stages them and
        reproduces the exact single-session error surface."""
        coalescer = getattr(self.store, "coalescer", None)
        if (
            coalescer is None
            or txn.explicit
            or txn.locked
            or not self.sysvars.get_bool("tidb_tpu_enable_coalesce")
            or len(txn.mutations)
            > self.sysvars.get_int("tidb_tpu_coalesce_max_write_keys")
        ):
            return None
        return coalescer.group_commit(
            txn.mutations, txn.start_ts,
            tag=topsql.current_tag(),
            wait_us=self.sysvars.get_int("tidb_tpu_coalesce_wait_us"),
            max_lanes=self.sysvars.get_int("tidb_tpu_coalesce_max_lanes"),
        )

    def _rollback(self):
        txn, self.txn = self.txn, None
        if txn is not None:
            from ..util import metrics

            metrics.OPEN_TXNS.dec()
            self.store.unregister_snapshot(txn.start_ts)
            self.store.txn.release_all(txn.start_ts)

    def _autocommit_dml(self, fn):
        """Run a DML statement inside the open txn (with a statement
        savepoint: a failed statement buffers nothing), or wrap it in an
        implicit single-statement txn (autocommit -> immediate 2PC)."""
        if self.sysvars.get("tidb_snapshot"):
            # ref: sessiontxn/staleread — a historical read session is
            # read-only until tidb_snapshot is cleared
            raise SQLError("can not execute write statement when 'tidb_snapshot' is set")
        if self.txn is not None:
            sp = self.txn.savepoint()
            try:
                return fn()
            except Exception:
                self.txn.restore(sp)
                raise
        self._begin(explicit=False)
        try:
            res = fn()
        except Exception:
            self._rollback()
            raise
        self._commit()
        return res

    def _implicit_commit(self):
        """DDL commits any open transaction first (MySQL semantics); a
        stale-read session (tidb_snapshot set) is read-only — DDL is
        rejected like DML (ref: sessiontxn/staleread restrictions)."""
        if self.sysvars.get("tidb_snapshot"):
            raise SQLError("can not execute DDL when 'tidb_snapshot' is set")
        if self.txn is not None:
            self._commit()

    def _lock_rows(self, meta: TableMeta, handles):
        """Pessimistic intention locks at DML/SELECT-FOR-UPDATE time
        (explicit pessimistic txns only; autocommit statements commit
        immediately so prewrite conflict checks suffice). Partitioned
        tables lock the handle's key in EVERY partition — over-locking is
        sound, and the row's partition is value-dependent."""
        from ..store.txn import TxnError

        if self.txn is None or not self.txn.explicit or self.txn.mode != "pessimistic":
            return
        keys = [
            tablecodec.encode_row_key(pid, h)
            for h in handles
            for pid in meta.physical_ids()
        ]
        if not keys:
            return
        # conflict bound = the txn's snapshot ts: a commit that landed after
        # our snapshot means this statement computed against stale rows —
        # fail with a retryable conflict instead of losing the update.
        # (TiDB instead re-reads at for_update_ts; stricter is still sound.)
        try:
            self.store.txn.acquire_pessimistic(keys, keys[0], self.txn.start_ts, self.txn.start_ts)
        except TxnError as exc:
            raise SQLError(str(exc)) from exc
        self.txn.locked |= set(keys)

    # ------------------------------------------------- buffered write path
    # row_ops stays keyed by the LOGICAL table id (handles are unique
    # across partitions — one shared allocator); only the kv key routes
    # to the row's physical partition (ref: tablecodec keys carry the
    # PartitionDefinition.ID for partitioned tables)
    def _buf_put_row(self, meta: TableMeta, handle: int, datums: list):
        key = tablecodec.encode_row_key(meta.pid_for_row(datums), handle)
        self.txn.mutations[key] = self.store._row_encoder.encode(meta.col_ids(), datums)
        self.txn.row_ops.setdefault(meta.table_id, {})[handle] = list(datums)

    def _buf_delete_row(self, meta: TableMeta, handle: int, row: list | None = None):
        pid = meta.pid_for_row(row) if (meta.partition is not None and row is not None) else meta.table_id
        if meta.partition is not None and row is None:
            # partition unknown: tombstone the handle in every partition
            for p in meta.physical_ids():
                self.txn.mutations[tablecodec.encode_row_key(p, handle)] = None
        else:
            self.txn.mutations[tablecodec.encode_row_key(pid, handle)] = None
        self.txn.row_ops.setdefault(meta.table_id, {})[handle] = None

    # ------------------------------------------------------------------
    def execute(self, sql: str) -> Result:
        """Parse + execute one statement through the admission gate,
        feeding the slow-query log and statement summary (ref:
        ExecStmt.Exec wrapping + LogSlowQuery, adapter.go:458/1580;
        pkg/util/stmtsummary Add). ONE lexer pass up front builds the
        plan-cache probe AND the normalized digest the statement log
        reuses — the hot path lexes once (ISSUE 15)."""
        import time as _time
        from contextlib import nullcontext

        from ..util import metrics, tracing
        from .plancache import StmtProbe, stmt_kind_reason

        t0 = _time.perf_counter()
        c0 = _time.thread_time()
        self._last_plan_digest = ""
        stmt_type = "invalid"
        probe = StmtProbe.from_sql(sql)
        saved = (self._stmt_probe, self._last_sql, self._record_digest)
        self._stmt_probe, self._last_sql = probe, sql
        self._record_digest = (probe.normalized, probe.digest) if probe else None
        # Top SQL resource tag: ONE per statement, riding the probe's
        # literal-masked digest from the same lexer pass — every layer
        # below (dispatch workers, store, Backoffer, admission queue)
        # attributes into it ambiently (ISSUE 17)
        tag = None
        if probe is not None and self.sysvars.get_bool("tidb_enable_top_sql"):
            tag = topsql.ResourceTag(probe.digest, sample_sql=sql[:256])
        tag_token = topsql.activate(tag)
        gate = getattr(self.store, "admission", None)
        try:
            try:
                # admission gate: saturated servers shed HERE, before any
                # parse/plan/dispatch work happens (typed ServerIsBusy).
                # The digest rides along: cost-classed mode weighs the
                # statement by its measured class
                with (gate.admit(id(self), digest=probe.digest if probe is not None else None)
                      if gate is not None else nullcontext()):
                    res = self._plan_cache_text_serve(probe)
                    if res is not None:
                        # parse-free hit: the digest-keyed entry served the
                        # statement with literal values bound straight from
                        # the lexer's masked tokens — no parse, no plan
                        # ("select", or "update"/"delete" for the pointwrite
                        # tier, ISSUE 19)
                        stmt_type = self._text_serve_type
                    else:
                        with tracing.span("session.parse", sql=sql[:256]):
                            stmt = parse_one(sql)
                        stmt_type = type(stmt).__name__.removesuffix("Stmt").lower()
                        if isinstance(stmt, A.ExplainStmt):
                            # the cache probe of EXPLAIN [ANALYZE] <stmt> is
                            # the INNER statement's — it shares entries with
                            # its direct form (satellite: attributable rows)
                            self._stmt_probe = StmtProbe.inner_probe(sql, "explain")
                        elif isinstance(stmt, A.TraceStmt):
                            self._stmt_probe = StmtProbe.inner_probe(sql, "trace")
                        elif (probe is not None
                              and not isinstance(stmt, (A.PrepareStmt, A.ExecuteStmt,
                                                        A.DeallocateStmt))):
                            reason = stmt_kind_reason(stmt)
                            if reason is not None:
                                # the probe belongs to THIS statement's text:
                                # a non-SELECT kind must drop it before any
                                # nested _run_select (INSERT..SELECT, CREATE
                                # VIEW) could install the inner select under
                                # the OUTER statement's digest — a later
                                # digest-equal statement would then serve
                                # rows instead of running the DML
                                self._stmt_probe = None
                                if self.sysvars.get_bool("tidb_enable_plan_cache"):
                                    metrics.PLAN_CACHE_DECLINES.labels(reason).inc()
                                    self._last_plan_cache = ("decline", reason, "")
                        res = self.execute_stmt(stmt)
            except Exception as exc:
                from ..distsql.dispatch import CopInternalError, RegionUnavailableError
                from ..distsql.runaway import QueryKilledError
                from ..server.admission import AdmissionShed

                metrics.STATEMENTS.labels(stmt_type, "error").inc()
                self._record_stmt(sql, (_time.perf_counter() - t0) * 1e3, 0, False, str(exc),
                                  cpu_ms=(_time.thread_time() - c0) * 1e3)
                if isinstance(exc, AdmissionShed):
                    # shed at the front door: MySQL 9003 "TiKV server busy"
                    # with the suggested wait riding the wire-format message,
                    # so clients classify via parse_region_error and retry on
                    # the existing server_busy Backoffer budget (PR-6 ride)
                    err = SQLError(str(exc), code=9003)
                    err.backoff_ms = exc.backoff_ms
                    raise err from exc
                if isinstance(exc, QueryKilledError):
                    # 3024 ER_QUERY_TIMEOUT (deadline) vs 1317 ER_QUERY_INTERRUPTED
                    # (KILL QUERY) — same split the reference makes
                    code = 3024 if getattr(exc, "timeout", False) else 1317
                    raise SQLError(str(exc), code=code) from exc
                if isinstance(exc, RegionUnavailableError):
                    # every backoff budget spent / every store unhealthy:
                    # MySQL 9005 (ref: errno.ErrRegionUnavailable), not a bare
                    # RuntimeError that reads like an engine bug
                    raise SQLError(f"Region is unavailable: {exc}", code=9005) from exc
                if isinstance(exc, QuorumLostError):
                    # a write refused on quorum loss (ROADMAP PR-8 follow-on):
                    # the same 9005 the read path's exhausted budgets surface
                    raise SQLError(f"Region is unavailable: {exc}", code=9005) from exc
                if isinstance(exc, CopInternalError):
                    raise SQLError(str(exc), code=1105) from exc
                raise
            metrics.STATEMENTS.labels(stmt_type, "ok").inc()
            rows = len(res.rows) if getattr(res, "rows", None) else getattr(res, "affected", 0)
            self._record_stmt(sql, (_time.perf_counter() - t0) * 1e3, rows, True,
                              cpu_ms=(_time.thread_time() - c0) * 1e3)
            return res
        finally:
            self._stmt_probe, self._last_sql, self._record_digest = saved
            topsql.deactivate(tag_token)

    def _record_stmt(self, sql: str, dur_ms: float, rows: int, ok: bool, err: str = "", cpu_ms: float = 0.0):
        try:

            # flush the statement's resource tag: host CPU lands here (the
            # exact thread_time delta — parse+plan+dispatch), the sinks
            # already accumulated device/compile/backoff/queue; EXECUTE
            # re-points the digest at the UNDERLYING prepared statement
            # (same join the stmt log makes via _record_digest)
            attr = None
            tag = topsql.current_tag()
            if tag is not None:
                rd = getattr(self, "_record_digest", None)
                if rd is not None:
                    tag.sql_digest = rd[1]
                pd_ = getattr(self, "_last_plan_digest", "")
                if pd_:
                    tag.plan_digest = pd_
                attr = tag.finish(int(cpu_ms * 1e6))
                pc = getattr(self, "_last_plan_cache", None)
                topsql.COLLECTOR.record_statement(
                    attr, success=ok,
                    plan_cache_hit=bool(pc and pc[0] == "hit"))
            thr = None
            if self.sysvars.get_bool("tidb_enable_slow_log"):
                t = self.sysvars.get_int("tidb_slow_log_threshold")
                thr = float(t) if t >= 0 else None
            self.catalog.stmtlog.record(
                sql, dur_ms, rows, ok, err,
                slow_threshold_ms=thr,
                summary_enabled=self.sysvars.get_bool("tidb_enable_stmt_summary"),
                cpu_ms=cpu_ms,
                plan_digest=getattr(self, "_last_plan_digest", ""),
                # EXECUTE records under the UNDERLYING prepared statement's
                # digest (set by _execute_prepared), joining its summary row
                # instead of orphaning on the "EXECUTE s" shape; direct
                # statements reuse the probe's digest — one lex per stmt
                norm_digest=getattr(self, "_record_digest", None),
                attr=attr,
            )
        except Exception:  # noqa: BLE001 — observability must never fail a query
            pass

    def execute_stmt(self, stmt) -> Result:
        self._qualify_tables(stmt)
        self._check_privileges(stmt)
        if isinstance(stmt, (A.SelectStmt, A.SetOprStmt, A.UpdateStmt, A.DeleteStmt, A.InsertStmt)):
            self._substitute_vars(stmt)
        if isinstance(stmt, A.SelectStmt):
            bound = self._match_binding(stmt)
            if bound is not None:
                stmt = bound  # same statement, binding hints grafted on
        if isinstance(stmt, A.PrepareStmt):
            # validate now; EXECUTE deep-copies the template per run (the
            # rewrite passes mutate ASTs; ref: plan_cache.go prepared-stmt
            # cache). The text + probe ride along so EXECUTE shares the
            # plan-cache entries and summary row of the DIRECT statement:
            # the prepared text normalizes with '?' markers exactly where
            # literals mask (ISSUE 15)
            from .plancache import StmtProbe

            self.prepared[stmt.name.lower()] = {
                "ast": parse_one(stmt.sql), "sql": stmt.sql,
                "probe": StmtProbe.from_sql(stmt.sql),
            }
            return Result()
        if isinstance(stmt, A.ExecuteStmt):
            return self._execute_prepared(stmt)
        if isinstance(stmt, A.DeallocateStmt):
            if self.prepared.pop(stmt.name.lower(), None) is None:
                raise SQLError(f"unknown prepared statement {stmt.name!r}")
            return Result()
        if isinstance(stmt, A.CreateUserStmt):
            from .privilege import PrivilegeError

            try:
                for name, host, pw in stmt.users:
                    self.catalog.privileges.create_user(name, host, pw, stmt.if_not_exists)
                    # mirror into mysql.user (ref: bootstrap.go + executor
                    # simple.go executeCreateUser writes the row directly);
                    # delete-then-insert keeps IF NOT EXISTS re-runs at one
                    # row, and quotes in names must be SQL-escaped
                    ne, he = _sql_str_escape(name), _sql_str_escape(host)
                    try:
                        self.execute(
                            f"delete from `mysql.user` where User = '{ne}' and Host = '{he}'"
                        )
                        self.execute(
                            "insert into `mysql.user` (Host, User, authentication_string, plugin) "
                            f"values ('{he}', '{ne}', '', 'mysql_native_password')"
                        )
                    except SQLError:
                        pass
            except PrivilegeError as exc:
                raise SQLError(str(exc)) from exc
            return Result()
        if isinstance(stmt, A.DropUserStmt):
            from .privilege import PrivilegeError

            try:
                for name, host in stmt.users:
                    self.catalog.privileges.drop_user(name, host, stmt.if_exists)
                    ne, he = _sql_str_escape(name), _sql_str_escape(host)
                    try:
                        self.execute(
                            f"delete from `mysql.user` where User = '{ne}' and Host = '{he}'"
                        )
                    except SQLError:
                        pass
            except PrivilegeError as exc:
                raise SQLError(str(exc)) from exc
            return Result()
        if isinstance(stmt, (A.GrantStmt, A.RevokeStmt)):
            from .privilege import PrivilegeError

            op = self.catalog.privileges.revoke if isinstance(stmt, A.RevokeStmt) else self.catalog.privileges.grant
            try:
                for name, host in stmt.users:
                    op(stmt.privs, stmt.db, stmt.table, name, host)
            except PrivilegeError as exc:
                raise SQLError(str(exc)) from exc
            return Result()
        if isinstance(stmt, A.SelectStmt):
            return self._select(stmt)
        if isinstance(stmt, A.SetOprStmt):
            names, fts, rows = self._set_opr(stmt, None)
            return Result(columns=names, rows=self._apply_select_limit(stmt, rows), fts=fts)
        if isinstance(stmt, A.CreateTableStmt):
            self._implicit_commit()
            self.catalog.create_table(stmt)
            self._persist_schema()
            return Result()
        if isinstance(stmt, A.DropTableStmt):
            self._implicit_commit()
            for t in stmt.tables:
                self.catalog.drop_table(t.name, stmt.if_exists)
            self._persist_schema()
            return Result()
        if isinstance(stmt, A.CreateViewStmt):
            self._implicit_commit()
            if not stmt.source:
                raise SQLError("CREATE VIEW requires a SELECT body")
            # validate: the body must plan against the current schema, and
            # an explicit column list must match the select-list arity
            # (ref: ddl CreateView checking the underlying plan). Plan-only
            # when possible — MySQL validates without executing; bodies the
            # bare planner can't take (views/CTEs/subqueries inside) fall
            # back to executing a LIMIT-0 wrapper.
            names = None
            body = parse_one(stmt.source)
            self._qualify_tables(body)  # validation under the CURRENT db
            if isinstance(body, A.SelectStmt):
                try:
                    from .planner import plan_select

                    names = plan_select(body, self.catalog).column_names
                except Exception:  # noqa: BLE001 — rewriter-dependent body
                    names = None
            if names is None:
                inner = parse_one(stmt.source)
                self._qualify_tables(inner)
                if getattr(inner, "limit", None) is None:
                    inner.limit = A.Limit(A.Literal(0, "int"))
                names, _, _ = self._run_select(inner, None) if isinstance(inner, A.SelectStmt) \
                    else self._set_opr(inner, None)
            if stmt.columns and len(stmt.columns) != len(names):
                raise SQLError(
                    f"view column list arity {len(stmt.columns)} != select list {len(names)}"
                )
            self.catalog.create_view(stmt.name.name, stmt.columns, stmt.source, stmt.or_replace)
            self._persist_schema()
            return Result()
        if isinstance(stmt, A.DropViewStmt):
            self._implicit_commit()
            for t in stmt.names:
                self.catalog.drop_view(t.name if hasattr(t, "name") else t, stmt.if_exists)
            self._persist_schema()
            return Result()
        if isinstance(stmt, A.TruncateTableStmt):
            self._implicit_commit()
            r = self._autocommit_dml(lambda: self._truncate(stmt))
            self._persist_schema()
            return r
        if isinstance(stmt, A.InsertStmt):
            return self._autocommit_dml(lambda: self._insert(stmt))
        if isinstance(stmt, A.UpdateStmt):
            return self._run_dml_cached(stmt, self._update)
        if isinstance(stmt, A.DeleteStmt):
            return self._run_dml_cached(stmt, self._delete)
        if isinstance(stmt, A.BeginStmt):
            # BEGIN implicitly commits any open txn (MySQL semantics)
            self._implicit_commit()
            self._begin(explicit=True)
            return Result()
        if isinstance(stmt, A.CommitStmt):
            self._commit()
            return Result()
        if isinstance(stmt, A.SavepointStmt):
            # named savepoints over the statement-savepoint machinery
            # (ref: session savepoint support, pkg/session savepoint ops)
            if stmt.action == "set":
                if self.txn is not None:
                    self.txn.named_savepoints[stmt.name] = self.txn.savepoint()
            elif stmt.action == "rollback":
                if self.txn is None or stmt.name not in self.txn.named_savepoints:
                    raise SQLError(f"SAVEPOINT {stmt.name} does not exist")
                sp = self.txn.named_savepoints[stmt.name]
                self.txn.restore(sp)
            else:  # release
                if self.txn is None or stmt.name not in self.txn.named_savepoints:
                    raise SQLError(f"SAVEPOINT {stmt.name} does not exist")
                del self.txn.named_savepoints[stmt.name]
            return Result()
        if isinstance(stmt, A.RollbackStmt):
            self._rollback()
            return Result()
        if isinstance(stmt, A.SetStmt):
            from .sysvar import SysVarError

            for scope, name, val in stmt.assignments:
                if not isinstance(val, A.Literal):
                    continue
                if name == "__set_names__":
                    # SET NAMES cs [COLLATE c] (ref: pkg/executor/set.go
                    # setCharset): client/connection/results take cs;
                    # collation_connection takes the explicit COLLATE, the
                    # default_collation_for_utf8mb4 override, or the
                    # charset default (TiDB: *_bin for utf8/utf8mb4,
                    # collate.GetDefaultCollation)
                    cs, _, coll = str(val.value).partition("|")
                    if not coll:
                        if cs == "utf8mb4":
                            try:
                                coll = self.sysvars.get("default_collation_for_utf8mb4")
                            except Exception:
                                coll = ""
                        coll = coll or {
                            "utf8mb4": "utf8mb4_bin", "utf8": "utf8_bin",
                            "gbk": "gbk_chinese_ci",
                            "gb18030": "gb18030_chinese_ci",
                            "latin1": "latin1_bin", "ascii": "ascii_bin",
                            "binary": "binary",
                        }.get(cs, cs + "_bin")
                    for v in ("character_set_client", "character_set_connection",
                              "character_set_results"):
                        self.sysvars.set(v, cs)
                    self.sysvars.set("collation_connection", coll)
                    continue
                if scope == "user":
                    self.user_vars[name.lower()] = str(val.value)
                else:
                    if name.lower() == "tidb_snapshot" and self.txn is not None:
                        # ref: TiDB rejects flipping stale-read mode inside
                        # an open txn (it would take effect only at COMMIT)
                        raise SQLError(
                            "can not set 'tidb_snapshot' inside a transaction"
                        )
                    try:
                        self.sysvars.set(name, str(val.value))
                    except SysVarError as exc:
                        raise SQLError(str(exc)) from exc
                    if name.lower() == "block_encryption_mode":
                        from . import builtins_host

                        builtins_host.BLOCK_ENCRYPTION_MODE = str(val.value)
                    elif name.lower() == "tidb_enable_top_sql":
                        # the collector is process-wide (one ledger per
                        # server, like the reference's single reporter):
                        # the sysvar bridges to it at SET time

                        topsql.COLLECTOR.configure(
                            enabled=self.sysvars.get_bool("tidb_enable_top_sql"))
                    elif name.lower() == "tidb_top_sql_max_statement_count":

                        topsql.COLLECTOR.configure(
                            top_k=self.sysvars.get_int("tidb_top_sql_max_statement_count"))
            return Result()
        if isinstance(stmt, A.UseStmt):
            db = stmt.db.lower()
            if db not in self.catalog.databases and db not in ("information_schema", "mysql"):
                raise SQLError(f"unknown database {db!r}")
            self.db = db
            return Result()
        if isinstance(stmt, A.CreateDatabaseStmt):
            db = stmt.name.lower()
            if db in self.catalog.databases and not stmt.if_not_exists:
                raise SQLError(f"database {db!r} already exists")
            self.catalog.databases.add(db)
            self._persist_schema()
            return Result()
        if isinstance(stmt, A.DropDatabaseStmt):
            db = stmt.name.lower()
            if db not in self.catalog.databases:
                if stmt.if_exists:
                    return Result()
                raise SQLError(f"unknown database {db!r}")
            if db == "test":
                raise SQLError("cannot drop the default database")
            self._implicit_commit()
            for t in [n for n in self.catalog.tables() if n.startswith(db + ".")]:
                self.catalog.drop_table(t)
            with self.catalog._lock:
                for v in [n for n in list(self.catalog.views) if n.startswith(db + ".")]:
                    del self.catalog.views[v]
            self.catalog.databases.discard(db)
            if self.db == db:
                self.db = "test"
            self._persist_schema()
            return Result()
        if isinstance(stmt, A.CreateIndexStmt):
            self._implicit_commit()
            r = self._create_index(stmt)
            self._persist_schema()
            return r
        if isinstance(stmt, A.DropIndexStmt):
            self._implicit_commit()
            r = self._drop_index(stmt)
            self._persist_schema()
            return r
        if isinstance(stmt, A.LoadDataStmt):
            from ..store.txn import TxnError
            from ..tools.lightning import load_data

            self._implicit_commit()
            # the bulk-ingest lock check raises KeyIsLocked when a live
            # 2PC holds a conflicting key — map it like every other txn
            # conflict (vet dataflow-error-escape: this used to reach the
            # client as a raw Python exception)
            try:
                return Result(affected=load_data(self, stmt))
            except TxnError as exc:
                raise SQLError(str(exc)) from exc
        if isinstance(stmt, A.BRIEStmt):
            from ..br import LogGapError, restore_until, start_log_backup, stop_log_backup
            from ..cdc import ChangefeedError
            from ..store.txn import TxnError
            from ..tools import backup, restore

            self._implicit_commit()
            try:
                if stmt.kind == "backup_log":
                    lb = start_log_backup(self.store, self.catalog, stmt.storage)
                    row = [Datum.string(stmt.storage), Datum.string(lb.feed_name),
                           Datum.i64(lb.start_ts)]
                    return Result(columns=["Destination", "Changefeed", "StartTS"],
                                  rows=[row])
                if stmt.kind == "stop_backup_log":
                    stop_log_backup(self.store, stmt.storage)
                    return Result()
                if stmt.kind == "backup":
                    m = backup(self.store, self.catalog, stmt.storage)
                    row = [Datum.string(stmt.storage), Datum.i64(m["total_keys"]), Datum.i64(m["snapshot_ts"])]
                    return Result(columns=["Destination", "Keys", "SnapshotTS"], rows=[row])
                if stmt.until_ts is not None:
                    info = restore_until(self.store, self.catalog, stmt.storage,
                                         stmt.until_ts)
                    row = [Datum.string(stmt.storage), Datum.i64(info["until_ts"]),
                           Datum.i64(info["segments_replayed"]),
                           Datum.i64(info["events_applied"])]
                    return Result(columns=["Source", "UntilTS", "Segments", "Events"],
                                  rows=[row])
                info = restore(self.store, self.catalog, stmt.storage)
                row = [Datum.string(stmt.storage), Datum.i64(info["keys"]), Datum.i64(info["tables"])]
                return Result(columns=["Source", "Keys", "Tables"], rows=[row])
            except (TxnError, LogGapError, ChangefeedError, ValueError) as exc:
                # RESTORE's bulk_ingest hits a held lock, a PITR coverage
                # gap, a duplicate/unknown log backup, a table collision:
                # every failure is a typed SQL error, never a raw Python
                # stack (vet dataflow-error-escape)
                raise SQLError(str(exc)) from exc
        if isinstance(stmt, A.AlterTableStmt):
            from .ddl import DDLError, alter_table

            self._implicit_commit()
            try:
                alter_table(self, stmt)
            except DDLError as exc:
                raise SQLError(str(exc)) from exc
            self._persist_schema()
            return Result()
        if isinstance(stmt, A.RenameTableStmt):
            from .ddl import DDLError, _rename_table, run_job

            self._implicit_commit()
            try:
                for old, new in stmt.pairs:
                    meta = self.catalog.table(old.name)
                    new_name = new.name if isinstance(new, A.TableName) else str(new)
                    run_job(self.catalog, "rename table", meta.name,
                            f"RENAME TABLE {old.name} TO {new_name}",
                            lambda m=meta, n=new_name: _rename_table(self.catalog, m, n))
            except DDLError as exc:
                raise SQLError(str(exc)) from exc
            self._persist_schema()
            return Result()
        if isinstance(stmt, A.BindingStmt):
            return self._binding(stmt)
        if isinstance(stmt, A.LoadStatsStmt):
            # LOAD STATS json (ref: pkg/statistics/handle LoadStatsFromJSON):
            # loads the dump when the file exists; the integration corpus'
            # fixture dir is not shipped in this tree, so a missing file is
            # tolerated exactly like the reference harness' pre-loaded state
            import os as _os

            p = stmt.path
            if not _os.path.isabs(p):
                p = _os.path.join("/root/reference/tests/integrationtest", p)
            if _os.path.exists(p):
                try:
                    self._load_stats_json(p)
                except Exception as exc:  # noqa: BLE001
                    raise SQLError(f"load stats: {exc}") from exc
            return Result()
        if isinstance(stmt, A.ChangefeedStmt):
            return self._changefeed(stmt)
        if isinstance(stmt, A.AdminStmt):
            return self._admin(stmt)
        if isinstance(stmt, A.AnalyzeTableStmt):
            return self._analyze(stmt)
        if isinstance(stmt, A.ShowStmt):
            return self._show(stmt)
        if isinstance(stmt, A.ExplainStmt):
            return self._explain(stmt)
        if isinstance(stmt, A.TraceStmt):
            return self._trace(stmt)
        raise SQLError(f"statement {type(stmt).__name__} not supported yet")

    def _changefeed(self, stmt: A.ChangefeedStmt) -> Result:
        """CREATE/PAUSE/RESUME/DROP CHANGEFEED (ref: TiCDC's changefeed
        lifecycle, SQL-ified like BACKUP/RESTORE). A registered vet
        request-path root: typed CDC errors must surface as SQLError."""
        from ..cdc import ChangefeedError, SinkError

        hub = self.store.cdc
        try:
            if stmt.action == "create":
                table_ids = None
                if stmt.tables:
                    ids = set()
                    for t in stmt.tables:
                        try:
                            meta = self.catalog.table(t.name)
                        except CatalogError as exc:
                            raise SQLError(str(exc)) from exc
                        ids.add(meta.table_id)
                        ids.update(meta.physical_ids())
                    table_ids = ids
                unknown = set(stmt.options) - {"start_ts"}
                if unknown:
                    # a typo'd option silently changing behavior is worse
                    # than an error (TiCDC rejects unknown options too)
                    raise SQLError(
                        f"unknown changefeed option(s) {sorted(unknown)}; "
                        f"supported: start_ts")
                raw_ts = stmt.options.get("start_ts", 0)
                if isinstance(raw_ts, bool) or not isinstance(raw_ts, int):
                    # a valueless `WITH start_ts` parses as True; a quoted
                    # value as str — both must be typed errors, not a raw
                    # ValueError escaping the boundary (review finding)
                    raise SQLError(
                        f"changefeed start_ts must be an integer TSO, got {raw_ts!r}")
                hub.create(stmt.name, stmt.sink_uri, self.catalog,
                           table_ids=table_ids, start_ts=raw_ts)
            elif stmt.action == "pause":
                hub.pause(stmt.name)
            elif stmt.action == "resume":
                hub.resume(stmt.name)
            elif stmt.action == "drop":
                hub.drop(stmt.name)
            else:
                raise SQLError(f"unknown changefeed action {stmt.action!r}")
        except (ChangefeedError, SinkError) as exc:
            raise SQLError(str(exc)) from exc
        return Result()

    def _trace(self, stmt: A.TraceStmt) -> Result:
        """TRACE [FORMAT='row'|'json'] <stmt> (ref: executor/trace.go
        TraceExec + pkg/util/tracing): run the statement on its NORMAL
        execution path under a root span — every layer's instrumentation
        (plan, dispatch, per-region cop tasks, program compile/cache,
        store decode/execute) attaches children — and return the span tree
        as the result set. A failing statement still returns the partial
        tree, with the error recorded on the failing span."""
        from ..util import tracing

        with tracing.trace("session", stmt=type(stmt.target).__name__) as root:
            try:
                with tracing.span("session.execute"):
                    inner = self.execute_stmt(stmt.target)
                root.set("rows", len(inner.rows) if inner.rows else inner.affected)
            except Exception as exc:  # noqa: BLE001 — the tree IS the result
                root.set("error", str(exc))
        if stmt.format == "json":
            return Result(columns=["trace"], rows=[[Datum.string(root.to_json())]])
        rows = [
            [Datum.string(op), Datum.i64(start_us), Datum.i64(dur_us), Datum.string(attrs)]
            for op, start_us, dur_us, attrs in root.rows()
        ]
        return Result(columns=["operation", "start_us", "duration_us", "attrs"], rows=rows)

    @staticmethod
    def _value_literal(val) -> A.Literal:
        """Python value (user var / param) -> literal AST node."""
        if val is None:
            return A.Literal(None, "null")
        s = str(val)
        try:
            return A.Literal(int(s), "int")
        except ValueError:
            return A.Literal(s, "str")

    def _execute_prepared(self, stmt: A.ExecuteStmt) -> Result:
        """EXECUTE name [USING @a, @b]: deep-copy the template, bind
        parameter markers from user variables (ref: executor/prepared.go)."""
        import copy

        rec = self.prepared.get(stmt.name.lower())
        if rec is None:
            raise SQLError(f"unknown prepared statement {stmt.name!r}")
        ast2 = copy.deepcopy(rec["ast"])
        params = [self._value_literal(self.user_vars.get(v.lower())) for v in stmt.using]
        n_used = self._bind_params(ast2, params)
        if n_used != len(params):
            raise SQLError(
                f"prepared statement {stmt.name!r} expects {n_used} parameters, got {len(params)}"
            )
        probe = rec.get("probe")
        if probe is not None:
            # ride the statement summary under the UNDERLYING statement's
            # digest (ISSUE 15 satellite), and — for SELECT templates
            # only — the plan cache too: the bound literals carry their
            # marker token positions, so the slot audit and re-binding
            # work exactly as for the textual form. A prepared DML's
            # nested select must NOT inherit the probe (its digest names
            # the whole DML text, not the inner select).
            self._record_digest = (probe.normalized, probe.digest)
            self._stmt_probe = probe if isinstance(ast2, A.SelectStmt) else None
        return self.execute_stmt(ast2)

    def _bind_params(self, node, params: list) -> int:
        """Replace ParamMarker nodes with the bound literals; returns the
        number of markers seen."""
        seen = [0]

        def sub(x):
            if isinstance(x, A.ParamMarker):
                # markers carry their LEXICAL position (parser assigns it),
                # which is the binding order MySQL uses — field traversal
                # order here may differ (e.g. Limit stores count before
                # offset). The bound literal inherits the marker's token
                # offset so the plan cache's slot collection sees it.
                seen[0] = max(seen[0], x.index + 1)
                if x.index >= len(params):
                    return A.Literal(None, "null", pos=x.pos)
                v = params[x.index]
                return A.Literal(v.value, v.kind, pos=x.pos)
            return None

        def walk_seq(v):
            for i, it in enumerate(v):
                if isinstance(it, A.ParamMarker):
                    v[i] = sub(it)
                elif isinstance(it, list):
                    walk_seq(it)
                elif isinstance(it, tuple):
                    v[i] = tuple(sub(x) if isinstance(x, A.ParamMarker) else x for x in it)
                    for x in v[i]:
                        if hasattr(x, "__dataclass_fields__"):
                            walk(x)
                elif hasattr(it, "__dataclass_fields__"):
                    walk(it)

        def walk(n):
            if not hasattr(n, "__dataclass_fields__"):
                return
            for f_ in n.__dataclass_fields__:
                v = getattr(n, f_)
                if isinstance(v, A.ParamMarker):
                    setattr(n, f_, sub(v))
                elif hasattr(v, "__dataclass_fields__"):
                    walk(v)
                elif isinstance(v, list):
                    walk_seq(v)

        walk(node)
        return seen[0]

    _PRIV_OF = {
        "InsertStmt": "insert", "UpdateStmt": "update", "DeleteStmt": "delete",
        "CreateTableStmt": "create", "DropTableStmt": "drop",
        "TruncateTableStmt": "drop", "CreateIndexStmt": "index",
        "DropIndexStmt": "index", "AlterTableStmt": "alter",
    }

    def _check_privileges(self, stmt):
        """(ref: privileges.RequestVerification called from the optimizer/
        executor adapters). Superusers skip; table scope is the statement's
        target (SELECT checks every referenced table)."""
        privs = self.catalog.privileges
        if privs.is_super(self.user):
            return
        kind = type(stmt).__name__
        if kind in ("GrantStmt", "RevokeStmt", "CreateUserStmt", "DropUserStmt",
                    "BRIEStmt", "ChangefeedStmt"):
            # changefeed admin follows BR: cluster-level replication is a
            # SUPER-only surface (ref: TiCDC requiring admin credentials)
            raise SQLError(f"access denied: {self.user!r} needs SUPER")
        if kind == "LoadDataStmt":
            if not privs.check(self.user, "insert", stmt.table.name, db=self.db):
                raise SQLError(f"access denied: {self.user!r} needs INSERT on {stmt.table.name!r}")
            return
        def check_read(names, exclude=()):
            for tname in names:
                if tname in exclude:
                    continue
                try:
                    self.catalog.table(tname)
                except CatalogError:
                    continue  # CTE/derived alias, not a real table
                if not privs.check(self.user, "select", tname, db=self.db):
                    raise SQLError(f"access denied: {self.user!r} needs SELECT on {tname!r}")

        need = self._PRIV_OF.get(kind)
        if need is not None:
            t = getattr(stmt, "table", None)
            tname = t.name.lower() if isinstance(t, A.TableName) else "*"
            if kind == "DropTableStmt":
                for t2 in stmt.tables:
                    if not privs.check(self.user, "drop", t2.name, db=self.db):
                        raise SQLError(f"access denied: {self.user!r} needs DROP on {t2.name!r}")
                return
            if not privs.check(self.user, need, tname, db=self.db):
                raise SQLError(f"access denied: {self.user!r} needs {need.upper()} on {tname!r}")
            # writes that read other tables (INSERT...SELECT, subqueries in
            # UPDATE/DELETE predicates) also need SELECT on the sources
            if kind in ("InsertStmt", "UpdateStmt", "DeleteStmt"):
                check_read(_referenced_tables(stmt), exclude={tname})
            return
        if kind in ("SelectStmt", "SetOprStmt", "AnalyzeTableStmt"):
            check_read(_referenced_tables(stmt))

    def _substitute_vars(self, node):
        """Rewrite @x / @@sysvar references to literals in place
        (ref: expression rewriter's variable substitution)."""

        def to_literal(v: A.Variable) -> A.Literal:
            if v.system:
                val = self.sysvars.get(v.name)
                from .sysvar import is_bool

                if is_bool(v.name):
                    # SELECT @@x prints booleans numerically (SHOW keeps
                    # ON/OFF) — MySQL/reference behavior
                    val = 1 if val == "ON" else 0
            else:
                val = self.user_vars.get(v.name.lower())
            return self._value_literal(val)

        for f_ in getattr(node, "__dataclass_fields__", {}):
            v = getattr(node, f_)
            if isinstance(v, A.Variable):
                setattr(node, f_, to_literal(v))
            elif isinstance(v, A.ExprNode) or hasattr(v, "__dataclass_fields__"):
                self._substitute_vars(v)
            elif isinstance(v, list):
                for i, it in enumerate(v):
                    if isinstance(it, A.Variable):
                        v[i] = to_literal(it)
                    elif isinstance(it, A.ExprNode) or hasattr(it, "__dataclass_fields__"):
                        self._substitute_vars(it)
                    elif isinstance(it, tuple):
                        v[i] = tuple(
                            to_literal(x) if isinstance(x, A.Variable) else x for x in it
                        )
                        for x in v[i]:
                            if isinstance(x, A.ExprNode):
                                self._substitute_vars(x)

    # ------------------------------------------------------------------
    def _apply_select_limit(self, stmt, rows):
        """MySQL sql_select_limit caps TOP-LEVEL result sets only — never
        subqueries/CTEs/views (those share _run_select recursively)."""
        if getattr(stmt, "limit", None) is not None:
            return rows
        ssl = self.sysvars.get_int("sql_select_limit")
        return rows[:ssl] if ssl < (1 << 64) - 1 else rows

    def _select(self, stmt: A.SelectStmt) -> Result:
        names, fts, rows = self._run_select(stmt, None)
        return Result(columns=names, rows=self._apply_select_limit(stmt, rows), fts=fts)

    def _persist_schema(self) -> None:
        """Write the catalog into the store's m-prefix keyspace after a
        schema change (ref: pkg/meta/meta.go — every DDL job persists its
        TableInfo; a reopened store recovers the schema from bytes)."""
        from .meta import persist_catalog

        persist_catalog(self.store, self.catalog)

    def _new_rewriter(self, parent_rw):
        from .subquery import SubqueryRewriter

        rw = SubqueryRewriter(
            self.catalog,
            registry=parent_rw.registry if parent_rw is not None else None,
            max_recursion=self.sysvars.get_int("cte_max_recursion_depth"),
            parent=parent_rw,
        )
        rw.exec_query = lambda q: self._exec_query(q, rw)
        return rw

    def _exec_query(self, stmt, parent_rw):
        """Nested-query entry: SelectStmt or SetOprStmt -> (names, fts, rows),
        sharing the parent rewriter's materialized-table namespace."""
        if isinstance(stmt, A.SetOprStmt):
            return self._set_opr(stmt, parent_rw)
        return self._run_select(stmt, parent_rw)

    def _run_select(self, stmt: A.SelectStmt, parent_rw) -> tuple:
        """Top-level SELECT entry: consult the digest-keyed plan cache
        first (ISSUE 15) — a hit re-binds the hot statement's literals
        into the cached template and skips parse+plan; a miss runs the
        normal pipeline and installs a slotted template on success.
        Nested queries (parent_rw set) never consult: their results feed
        a parent statement that owns the cache decision."""
        probe = self._take_probe() if parent_rw is None else None
        if probe is None:
            return self._run_select_inner(stmt, parent_rw)
        served, pending = self._plan_cache_begin(probe, stmt)
        if served is not None:
            return served
        out = self._run_select_inner(stmt, parent_rw)
        if pending is not None:
            self._plan_cache_install(probe, pending)
        return out

    def _take_probe(self):
        p, self._stmt_probe = self._stmt_probe, None
        return p

    # ------------------------------------------- plan cache (ISSUE 15)
    def _plan_cache_key(self, probe, kinds: str) -> tuple:
        """digest + db + literal-kind signature + plan-relevant sysvar
        fingerprint + session-binding revision. Schema drift and GLOBAL
        binding changes are validations on the entry, not key parts."""
        from .plancache import sysvar_fingerprint

        return (probe.digest, self.db, kinds,
                sysvar_fingerprint(self.sysvars), self._bindings_rev)

    def _plan_cache_text_serve(self, probe) -> Result | None:
        """The parse-free fast path (ref: TiDB's non-prepared plan cache
        keyed on the normalized digest): when the probe's digest already
        has a validated entry under the current db/kinds/sysvar/binding
        key, serve the statement by binding the lexer's masked-token
        values into the cached template — lexer-only, no parse, no plan.
        Returns None on any miss or ineligibility; the parse path then
        runs and counts its own miss/decline. Session-state declines
        (txn, stale read) re-check here because they vary per statement;
        structural shape was proven at install time and transfers to
        every digest-equal statement."""
        from ..util import metrics, tracing
        from . import plancache as _pc

        if (probe is None or probe.has_param or probe.has_var
                or probe.multi_stmt or probe.n_masked == 0
                or not self.sysvars.get_bool("tidb_enable_plan_cache")
                or self.txn is not None
                or self.sysvars.get("tidb_snapshot")):
            # n_masked == 0 shapes stay on the parse path: binding cannot
            # distinguish them from DDL/EXPLAIN/SET text anyway, and the
            # entry lookup would land on keys the install path never fills
            return None
        self._text_serve_type = "select"
        key = self._plan_cache_key(probe, probe.slot_kinds)
        entry = self.catalog.plan_cache.lookup(
            key, self.catalog, self.catalog.bindings_rev)
        if entry is None:
            entry = self._plan_cache_shared_adopt(key)
        if entry is None:
            return None
        if entry.tier == "pointwrite":
            # DML point-write tier (ISSUE 19): UPDATE/DELETE ... WHERE
            # pk = ? serves parse-free through the same digest machinery
            return self._plan_cache_serve_dml(entry, probe)
        with tracing.span("session.plan_cache") as sp:
            try:
                self._check_privileges(entry.template)
                out = self._plan_cache_execute(entry, list(probe.slot_values))
            except _pc.RebindError:
                return None  # recipe could not re-bind: replan cold
            metrics.PLAN_CACHE_HITS.inc()
            self._last_plan_cache = ("hit", "", entry.tier)
            self._stmt_probe = None  # consumed: nested paths never re-consult
            if sp is not None:
                sp.set("status", "hit")
                sp.set("tier", entry.tier)
        names, _fts, rows = out
        if not entry.has_limit:
            ssl = self.sysvars.get_int("sql_select_limit")
            if ssl < (1 << 64) - 1:
                rows = rows[:ssl]
        return Result(columns=names, rows=rows, fts=_fts)

    def _plan_cache_begin(self, probe, stmt):
        """Returns (served result, install ticket): a HIT serves the
        statement with parse+plan skipped; a MISS returns the ticket
        (key + pristine template copy) the success path installs; a
        DECLINE returns neither and counts its typed reason."""
        import copy as _copy

        from ..util import metrics, tracing
        from . import plancache as _pc

        if not self.sysvars.get_bool("tidb_enable_plan_cache"):
            self._last_plan_cache = ("off", "", "")
            return None, None
        with tracing.span("session.plan_cache") as sp:
            reason = _pc.shape_decline(stmt, self, probe)
            values = kinds = None
            if reason is None:
                try:
                    values, kinds = _pc.live_slot_values(stmt, probe.n_masked)
                except _pc.RebindError:
                    reason = "literal_shape"
            if reason is not None:
                metrics.PLAN_CACHE_DECLINES.labels(reason).inc()
                self._last_plan_cache = ("decline", reason, "")
                if sp is not None:
                    sp.set("status", "decline")
                    sp.set("reason", reason)
                return None, None
            key = self._plan_cache_key(probe, kinds)
            entry = self.catalog.plan_cache.lookup(
                key, self.catalog, self.catalog.bindings_rev)
            if entry is None:
                entry = self._plan_cache_shared_adopt(key)
            if entry is not None:
                try:
                    out = self._plan_cache_execute(entry, values)
                except _pc.RebindError:
                    out = None  # recipe could not re-bind: replan cold
                if out is not None:
                    metrics.PLAN_CACHE_HITS.inc()
                    self._last_plan_cache = ("hit", "", entry.tier)
                    if sp is not None:
                        sp.set("status", "hit")
                        sp.set("tier", entry.tier)
                    return out, None
            metrics.PLAN_CACHE_MISSES.inc()
            self._last_plan_cache = ("miss", "", "")
            if sp is not None:
                sp.set("status", "miss")
            return None, (key, _copy.deepcopy(stmt))

    def _plan_cache_execute(self, entry, values) -> tuple:
        """Serve a statement from a cached template. pointget re-executes
        the key-read fast path from the bound AST; dag re-binds Consts +
        ranges into the cached physical plan and goes straight to
        dispatch; ast re-plans the bound template (parse skipped)."""
        from . import plancache as _pc

        if entry.tier == "dag":
            plan = _pc.rebind_plan(entry, values, self.catalog)
            return self._execute_planned(plan)
        bound = _pc.bind_template(entry.template, values)
        if entry.tier == "pointget":
            det = self._point_get_detect(bound, {})
            if det is not None:
                # plan-cache-hit point gets are the coalescable tier
                # (ISSUE 19): the hint lets _exec_point_get park in the
                # store's micro-batch window instead of launching alone
                self._coalesce_hint = True
                try:
                    return self._exec_point_get(bound, *det)
                finally:
                    self._coalesce_hint = False
        return self._run_select_inner(bound, None)

    def _plan_cache_install(self, probe, pending) -> None:
        """Build + install the slotted template after the cold statement
        succeeded (one extra plan pass per digest, amortized over hits).
        Best-effort: an uncacheable shape counts a typed decline and the
        statement's result stands."""
        import copy as _copy

        from ..util import metrics
        from . import plancache as _pc

        key, tpl = pending
        try:
            kinds = _pc.wrap_slots(tpl, probe.n_masked)
            fps = {}
            for nm in _referenced_tables(tpl):
                try:
                    meta = self.catalog.table(nm)
                except CatalogError:
                    continue
                fps[meta.name] = _pc.table_fingerprint(meta)
            tier, plan2 = "ast", None
            range_src, probe_name, build_names = ("full",), "", ()
            if self._point_get_detect(tpl, {}) is not None:
                tier = "pointget"
            else:
                try:
                    tpl2 = _copy.deepcopy(tpl)
                    rw = self._new_rewriter(None)
                    rw.rewrite_select(tpl2)
                    if not rw.mat_dict():
                        plan2 = plan_select(
                            tpl2, self.catalog,
                            enable_index_merge=self.sysvars.get_bool(
                                "tidb_enable_index_merge"),
                        )
                except Exception:  # noqa: BLE001 — planner balked at the
                    plan2 = None  # slotted copy: ast tier still skips parse
                if plan2 is not None and self._dag_tier_ok(plan2, kinds,
                                                           probe.n_masked):
                    tier = "dag"
                    range_src = getattr(plan2, "range_src", None) or ("full",)
                    probe_name = plan2.probe_table.name
                    build_names = tuple(m.name for m in plan2.build_tables)
                else:
                    plan2 = None
            entry = _pc.PlanCacheEntry(
                tier=tier, template=tpl, n_slots=probe.n_masked, kinds=kinds,
                table_fps=fps, catalog_version=self.catalog.version,
                bindings_rev=self.catalog.bindings_rev,
                has_limit=tpl.limit is not None,
                plan=plan2, range_src=range_src, probe_name=probe_name,
                build_names=build_names,
            )
            pc = self.catalog.plan_cache
            pc.capacity = self.sysvars.get_int("tidb_plan_cache_size")
            pc.put(key, entry)
            if self.sysvars.get_bool("tidb_tpu_plan_cache_shared"):
                _pc.publish_shared(key, entry, self.catalog.bindings_rev,
                                   self._bindings_rev)
        except Exception:  # noqa: BLE001 — install is best-effort; the
            metrics.PLAN_CACHE_DECLINES.labels("uncacheable").inc()
            self._last_plan_cache = ("decline", "uncacheable", "")

    def _plan_cache_shared_adopt(self, key):
        """Shared cross-catalog tier consult (ISSUE 19 satellite): on a
        local miss, adopt an entry another catalog's sessions installed
        for this digest — fingerprint-revalidated against OUR catalog,
        then promoted into the local cache so the next hit is local.
        Binding-active catalogs/sessions stay local: binding revisions
        don't transfer across catalogs."""
        from ..util import metrics
        from . import plancache as _pc

        if (not self.sysvars.get_bool("tidb_tpu_plan_cache_shared")
                or self.catalog.bindings_rev != 0 or self._bindings_rev != 0):
            return None
        entry = _pc.SHARED_CACHE.lookup_shared(key, self.catalog)
        if entry is None:
            return None
        metrics.PLAN_CACHE_SHARED_HITS.inc()
        self.catalog.plan_cache.put(key, entry)
        return entry

    def _plan_cache_serve_dml(self, entry, probe) -> Result | None:
        """Parse-free serve of a cached DML point-write (ISSUE 19): bind
        the lexer's masked-token values into the template and run the
        UPDATE/DELETE through the autocommit wrapper — the write reaches
        the group-commit window without a parse or plan."""
        from ..util import metrics
        from . import plancache as _pc

        try:
            self._check_privileges(entry.template)
            bound = _pc.bind_template(entry.template, list(probe.slot_values))
        except _pc.RebindError:
            return None  # recipe could not re-bind: replan cold
        self._stmt_probe = None  # consumed: nested paths never re-consult
        is_update = isinstance(bound, A.UpdateStmt)
        self._text_serve_type = "update" if is_update else "delete"
        # the hit counts only after the write succeeds: a conflict/abort
        # surfaces exactly as the parse path's would, uncounted
        res = self._autocommit_dml(
            lambda: self._update(bound) if is_update else self._delete(bound))
        metrics.PLAN_CACHE_HITS.inc()
        self._last_plan_cache = ("hit", "", entry.tier)
        return res

    def _run_dml_cached(self, stmt, fn) -> Result:
        """Top-level UPDATE/DELETE entry (ISSUE 19): point-write shapes
        (WHERE pk = ? / pk IN (...) on an unpartitioned int-handle table)
        install a `pointwrite` tier entry on success, so digest-equal
        statements serve parse-free through _plan_cache_serve_dml. Other
        shapes count a typed `dml_shape` decline. The statement itself
        always runs the normal autocommit pipeline."""
        import copy as _copy

        from ..util import metrics
        from . import plancache as _pc

        probe = self._take_probe()
        pending = None
        if probe is not None and not (
                probe.has_param or probe.has_var or probe.multi_stmt
                or probe.n_masked == 0):
            if not self.sysvars.get_bool("tidb_enable_plan_cache"):
                self._last_plan_cache = ("off", "", "")
            else:
                reason = self._dml_shape_decline(stmt)
                values = kinds = None
                if reason is None:
                    try:
                        values, kinds = _pc.live_slot_values(stmt, probe.n_masked)
                    except _pc.RebindError:
                        reason = "literal_shape"
                if reason is not None:
                    metrics.PLAN_CACHE_DECLINES.labels(reason).inc()
                    self._last_plan_cache = ("decline", reason, "")
                else:
                    metrics.PLAN_CACHE_MISSES.inc()
                    self._last_plan_cache = ("miss", "", "")
                    pending = (self._plan_cache_key(probe, kinds),
                               _copy.deepcopy(stmt))
        res = self._autocommit_dml(lambda: fn(stmt))
        if pending is not None:
            self._plan_cache_install_dml(probe, pending)
        return res

    def _dml_shape_decline(self, stmt) -> str | None:
        """Typed decline for non-point DML shapes (None = cacheable
        point write). Mirrors shape_decline's session checks, then
        requires the WHERE clause to be a pure pk-equality the handle
        extractor accepts."""
        if self.txn is not None:
            return "in_txn"
        if self.sysvars.get("tidb_snapshot"):
            return "stale_read"
        if getattr(stmt, "multi_table", False):
            return "dml_shape"
        tbl = getattr(stmt, "table", None)
        if not isinstance(tbl, A.TableName):
            return "dml_shape"
        if stmt.where is None:
            return "dml_shape"
        try:
            meta = self.catalog.table(tbl.name)
        except CatalogError:
            return "no_table"
        if meta.table_id < 0 or meta.partition is not None:
            return "dml_shape"
        if meta.handle_col is None:
            return "dml_shape"  # no int pk: handles aren't value-addressed
        alias = (tbl.alias or meta.name.rsplit(".", 1)[-1]).lower()
        if self._extract_pk_handles(meta, alias, stmt.where) is None:
            return "dml_shape"
        return None

    def _plan_cache_install_dml(self, probe, pending) -> None:
        """Install the slotted pointwrite template after the cold DML
        succeeded. Best-effort, like _plan_cache_install."""
        from ..util import metrics
        from . import plancache as _pc

        key, tpl = pending
        try:
            kinds = _pc.wrap_slots(tpl, probe.n_masked)
            fps = {}
            for nm in _referenced_tables(tpl):
                try:
                    meta = self.catalog.table(nm)
                except CatalogError:
                    continue
                fps[meta.name] = _pc.table_fingerprint(meta)
            entry = _pc.PlanCacheEntry(
                tier="pointwrite", template=tpl, n_slots=probe.n_masked,
                kinds=kinds, table_fps=fps,
                catalog_version=self.catalog.version,
                bindings_rev=self.catalog.bindings_rev,
                has_limit=True,  # a write returns no rows to trim
            )
            pc = self.catalog.plan_cache
            pc.capacity = self.sysvars.get_int("tidb_plan_cache_size")
            pc.put(key, entry)
            if self.sysvars.get_bool("tidb_tpu_plan_cache_shared"):
                _pc.publish_shared(key, entry, self.catalog.bindings_rev,
                                   self._bindings_rev)
        except Exception:  # noqa: BLE001 — install is best-effort; the
            metrics.PLAN_CACHE_DECLINES.labels("uncacheable").inc()
            self._last_plan_cache = ("decline", "uncacheable", "")

    def _dag_tier_ok(self, plan2, kinds: str, n_slots: int) -> bool:
        """May this plan be cached at the dag tier (skip parse AND plan)?
        Requires real tables, no partition pruning / index-merge (their
        range structure is value-dependent), a recomputable range recipe,
        and the full literal-slot audit (plancache.audit_dag_slots)."""
        from . import plancache as _pc

        if plan2.probe_table.table_id < 0 or any(
                m.table_id < 0 for m in plan2.build_tables):
            return False
        if plan2.probe_table.partition is not None or plan2.lookup_merge:
            return False
        src = getattr(plan2, "range_src", None)
        if src is None or src[0] == "partition":
            return False
        if plan2.lookup is not None and src[0] != "lookup":
            return False
        return _pc.audit_dag_slots(plan2, kinds, n_slots)

    def _run_select_inner(self, stmt: A.SelectStmt, parent_rw) -> tuple:
        from .subquery import SubqueryError

        rw = self._new_rewriter(parent_rw)
        try:
            rw.process_ctes(stmt.ctes)
            stmt.ctes = []
            if stmt.from_clause is None:
                # SELECT <exprs>: subqueries materialize, constants evaluate
                # with the reference evaluator
                for f in stmt.fields:
                    if isinstance(f, A.SelectField):
                        f.expr = rw._rewrite_expr(f.expr, [], stmt)
                lw = _Lowerer(_Scope([]))
                ev = RefEvaluator()
                exprs = [lw.lower_base(f.expr) for f in stmt.fields]
                from .planner import _field_label

                names = [_field_label(f) for f in stmt.fields]
                if stmt.where is not None:
                    # SELECT ... FROM DUAL WHERE <cond> (the only legal
                    # table-less WHERE form; ref: MySQL DUAL semantics)
                    w = rw._rewrite_expr(stmt.where, [], stmt)
                    from ..expr.eval_ref import _truth

                    if _truth(ev.eval(lw.lower_base(w), [])) is not True:
                        return names, [e.ft for e in exprs], []
                row = [ev.eval(e, []) for e in exprs]
                return names, [e.ft for e in exprs], [row]
            rw.rewrite_select(stmt)
        except SubqueryError as exc:
            raise SQLError(str(exc)) from exc
        self._bind_information_schema(stmt.from_clause, rw)
        if stmt.for_update:
            self._select_for_update(stmt)
        # the fast path's _read_row already overlays the txn buffer, so it
        # runs BEFORE dirty-table shadowing (which would materialize the
        # whole table just to read one key)
        fast = self._try_point_get(stmt, rw)
        if fast is not None:
            return fast
        if self.txn is not None and self.txn.row_ops:
            self._shadow_dirty_tables(stmt.from_clause, rw)
        plan = plan_select(
            stmt, self.catalog, mat=rw.mat_dict(),
            enable_index_merge=self.sysvars.get_bool("tidb_enable_index_merge"),
        )
        return self._execute_planned(plan, rw)

    def _execute_planned(self, plan, rw=None) -> tuple:
        """Execute a planned SELECT: the dispatch tail shared by the
        normal pipeline and dag-tier plan-cache hits (which arrive with a
        re-bound plan and no rewriter — cacheable shapes reference real
        tables only). Returns (column names, output fts, rows)."""
        from ..util.memory import MemTracker, QuotaExceeded

        # plan digest: access path + executor-shape fingerprint, the join
        # key between slow-log rows and statement summaries (ref:
        # plancodec.NormalizePlan -> plan_digest in the slow log)
        import hashlib as _hashlib

        self._last_plan_digest = _hashlib.sha256(
            f"{plan.access_path}|{plan.dag.fingerprint()}".encode()
        ).hexdigest()[:32]
        ts = self._pin_read_ts()
        # OOM action chain (ref: util/memory tracker actions): first evict
        # the store's reclaimable chunk/batch caches; a second breach is
        # handled below by degrading to the low-memory execution path
        evicted = [False]

        def _evict_action(tr, _n):
            if not evicted[0]:
                evicted[0] = True
                freed = self.store.evict_caches()
                from ..util import metrics

                metrics.MEM_EVICTIONS.inc()
                tr.consume(-min(freed, 0))  # caches are store-owned; the
                # eviction frees real memory but the tracker accounts query
                # bytes only — the retry below re-checks the quota

        tracker = MemTracker(
            "query",
            quota=self.sysvars.get_int("tidb_mem_quota_query") or None,
            parent=self._session_tracker(),
            action=_evict_action,
        )
        gate_on = self.sysvars.get_bool("tidb_enable_tpu_coprocessor")
        aux = []
        try:
            for t in plan.build_tables:
                c = self._table_chunk(t, ts, rw)
                tracker.consume(c.nbytes())
                aux.append(c)
            if plan.probe_table.table_id < 0:
                # materialized probe (CTE/derived table): the whole DAG runs
                # over in-memory chunks — device path or oracle by the gate
                # (never reached from a plan-cache hit: those shapes decline)
                probe = rw.registry.chunks[plan.probe_table.name]
                tracker.consume(probe.nbytes())
                if gate_on:
                    from ..exec import run_dag_on_chunks

                    chunk = run_dag_on_chunks(plan.dag, [probe] + aux)
                else:
                    from ..exec import run_dag_reference

                    rows = run_dag_reference(plan.dag, [probe] + aux)
                    chunk = Chunk.from_rows(plan.dag.output_fts(), rows)
            else:
                # empty ranges (ranger proved the predicate unsatisfiable)
                # flow through: execute_root dispatches zero tasks and the
                # root merge still produces scalar-agg rows
                if plan.ranges is not None:
                    ranges = plan.ranges
                else:
                    ranges = [
                        r for pid in plan.probe_table.physical_ids()
                        for r in full_table_ranges(pid)
                    ]
                if plan.lookup is not None or plan.lookup_merge:
                    # index-lookup double-read phase 1: index scan -> row
                    # handles -> coalesced table ranges (ref:
                    # pkg/executor/distsql.go IndexLookUpExecutor /
                    # index_merge_reader.go for the union form)
                    ranges = self._lookup_handle_ranges(plan, ts)
                if not gate_on:
                    # feature gate OFF (ref: TiDBAllowMPPExecution pattern):
                    # evaluate the whole plan with the row-at-a-time oracle
                    chunk = self._select_via_oracle(plan, ranges, aux, ts)
                else:
                    chunk = None
                    engines = self._read_engines()

                    def _columnar_routed():
                        # engine routing (ISSUE 12): when the columnar
                        # replica is this plan's engine, the whole-plan
                        # mesh shortcut must not preempt it — the consult
                        # itself lives in execute_root. Evaluated LAST in
                        # the mesh condition so the eligibility walk only
                        # runs when a mesh attempt is actually on the
                        # table (review finding: no double walk when mesh
                        # is off or EXPLAIN ANALYZE pinned the cop path)
                        from ..columnar.route import columnar_would_serve

                        return columnar_would_serve(
                            self.store, plan.dag, ranges, engines)

                    if self._explain_sink is None:
                        # EXPLAIN ANALYZE wants per-executor summaries,
                        # which only the per-region path produces.
                        # Statement tier (ref: mpp_gather.go:40): "mpp"
                        # plans exchange-linked fragments through the
                        # dispatch layer, "mesh" is the whole-plan
                        # shard_map shortcut, "root" defers to
                        # execute_root (per-request tiers + columnar)
                        from ..distsql.planner import choose_statement_tier

                        decision = choose_statement_tier(
                            plan.dag,
                            allow_mpp=self.sysvars.get_bool("tidb_allow_mpp"),
                            allow_mesh=self.sysvars.get_bool("tidb_enable_tpu_mesh"),
                            columnar_routed=_columnar_routed,
                        )
                        gc = self.sysvars.get_int("tidb_tpu_group_capacity")
                        if decision.tier == "mpp":
                            from ..mpp.dispatch import try_mpp_select

                            chunk = try_mpp_select(
                                self.store, plan.dag, ranges, ts,
                                group_capacity=gc,
                                aux_chunks=aux,
                                engines=engines,
                                backoff_weight=self.sysvars.get_int("tidb_backoff_weight"),
                                checker=self._runaway_checker(),
                            )
                        if (chunk is None
                                and decision.tier in ("mpp", "mesh")
                                and not (decision.tier == "mpp" and _columnar_routed())):
                            # mpp declined (counted fallback): the mesh
                            # shortcut still applies unless the columnar
                            # replica owns the plan (engine routing)
                            from ..parallel.sql import try_mesh_select

                            chunk = try_mesh_select(
                                self.store, plan.dag, ranges, ts,
                                group_capacity=gc,
                                aux_chunks=aux,
                            )
                    if chunk is None:
                        kwargs = dict(
                            start_ts=ts,
                            aux_chunks=aux,
                            group_capacity=self.sysvars.get_int("tidb_tpu_group_capacity"),
                            small_groups=plan.small_groups,
                            concurrency=self.sysvars.get_int("tidb_distsql_scan_concurrency"),
                            paging_size=(
                                self.sysvars.get_int("tidb_max_chunk_size")
                                if self.sysvars.get_bool("tidb_enable_paging")
                                else None
                            ),
                            batch_cop=self.sysvars.get_bool("tidb_allow_batch_cop"),
                            mesh=self.sysvars.get_bool("tidb_enable_tpu_mesh"),
                            mesh_min_rows=self.sysvars.get_int("tidb_tpu_mesh_min_rows"),
                            summary_sink=self._explain_sink,
                            checker=self._runaway_checker(),
                            backoff_weight=self.sysvars.get_int("tidb_backoff_weight"),
                            replica_read=self.sysvars.get("tidb_replica_read"),
                            isolation_engines=engines,
                        )
                        try:
                            chunk = execute_root(
                                self.store, plan.dag, ranges, tracker=tracker, **kwargs
                            )
                        except QuotaExceeded:
                            # degrade: sequential dispatch + incremental
                            # Partial2 fold keeps the working set bounded
                            # (the spill analog; VERDICT r2 next #10)
                            from ..util import metrics

                            metrics.MEM_DEGRADED_QUERIES.inc()
                            tracker.release_all()
                            chunk = execute_root(
                                self.store, plan.dag, ranges,
                                tracker=tracker, low_memory=True, **kwargs
                            )
            tracker.consume(chunk.nbytes())
        except QuotaExceeded as exc:
            raise SQLError(str(exc)) from exc
        finally:
            tracker.release_all()
            self._unpin_read_ts(ts)
        rows = chunk.rows()
        if plan.offset:
            rows = rows[plan.offset :]
        return plan.column_names, plan.dag.output_fts(), rows

    def _set_opr(self, stmt: A.SetOprStmt, parent_rw) -> tuple:
        """UNION [ALL] chains: branch results merge at root; a DISTINCT
        union dedups the entire accumulated set (MySQL semantics; ref:
        pkg/executor/union iterator + planner buildSetOpr)."""
        from ..expr.eval_ref import compare
        from .subquery import SubqueryError

        if any(op != "union" for op in getattr(stmt, "ops", [])):
            raise SQLError("EXCEPT/INTERSECT set operations are not supported yet")
        rw = self._new_rewriter(parent_rw)
        try:
            rw.process_ctes(stmt.ctes)
            stmt.ctes = []
        except SubqueryError as exc:
            raise SQLError(str(exc)) from exc
        # two passes: collect every branch, unify column types across them
        # (MySQL coerces all branches to one result type before dedup), then
        # fold with the per-boundary distinct flags
        from ..exec.executor import datum_group_key
        from .planner import _unify_fts

        names = None
        branches = []
        for sel in stmt.selects:
            n_, f_, r_ = self._exec_query(sel, rw)
            if names is None:
                names = n_
            elif len(n_) != len(names):
                raise SQLError("The used SELECT statements have a different number of columns")
            branches.append((f_, r_))
        fts = [
            _unify_fts([b[0][i] for b in branches])
            for i in range(len(names))
        ]
        acc: list = []
        for i, (bf, rows) in enumerate(branches):
            coerced = [
                [d if d.is_null() else _coerce_datum(d, ft) for d, ft in zip(r, fts)]
                for r in rows
            ]
            acc.extend(coerced)
            if i > 0 and not stmt.all_flags[i - 1]:
                seen: set = set()
                dedup = []
                for r in acc:
                    # collation-aware keys: ci strings dedup case-folded
                    k = tuple(datum_group_key(d, ft) for d, ft in zip(r, fts))
                    if k not in seen:
                        seen.add(k)
                        dedup.append(r)
                acc = dedup
        if stmt.order_by:
            import functools

            idxs = []
            for b in stmt.order_by:
                e = b.expr
                if isinstance(e, A.Literal) and e.kind == "int":
                    pos = int(e.value)
                    if not (1 <= pos <= len(names)):
                        raise SQLError(f"ORDER BY position {pos} out of range")
                    idxs.append((pos - 1, b.desc))
                elif isinstance(e, A.ColumnName) and not e.table:
                    low_names = [n.lower() for n in names]
                    if e.name.lower() not in low_names:
                        raise SQLError(f"unknown column {e.name!r} in UNION ORDER BY")
                    idxs.append((low_names.index(e.name.lower()), b.desc))
                else:
                    raise SQLError("UNION ORDER BY supports output columns and positions only")

            def cmp(a, b):
                for i, desc in idxs:
                    x, y = a[i], b[i]
                    if x.is_null() and y.is_null():
                        continue
                    c = -1 if x.is_null() else (1 if y.is_null() else compare(x, y))
                    if c:
                        return -c if desc else c
                return 0

            acc.sort(key=functools.cmp_to_key(cmp))
        if stmt.limit is not None:
            def _n(e, dflt):
                if e is None:
                    return dflt
                if isinstance(e, A.Literal):
                    return int(e.value)
                return int(e)

            off = _n(stmt.limit.offset, 0)
            cnt = _n(stmt.limit.count, len(acc))
            acc = acc[off : off + cnt]
        return names, fts, acc

    def _table_chunk(self, meta: TableMeta, ts: int, rw) -> Chunk:
        if meta.table_id < 0:
            return rw.registry.chunks[meta.name]
        return self._fetch_table_chunk(meta, ts)

    def _column_descs(self, meta: TableMeta) -> list:
        """(name, type, is_nullable, key, default, extra) per column —
        shared by SHOW COLUMNS and information_schema.columns."""
        from ..tools.dump import _type_sql

        pri_cols = set()
        for idx in meta.indices:
            if idx.name == "PRIMARY":
                pri_cols.update(idx.col_names)
        out = []
        for c in meta.columns:
            dflt = "NULL" if not c.ft.not_null() else ""
            if c.default is not None:
                try:
                    d = self._eval_const(c.default, c.ft)
                    dflt = "NULL" if d.is_null() else str(d.val)
                except Exception:  # noqa: BLE001 — display only
                    pass
            elif c.origin_default is not None and not c.origin_default.is_null():
                dflt = str(c.origin_default.val)
            out.append((
                c.name, (c.decl or _type_sql(c.ft).lower()),
                "NO" if c.ft.not_null() else "YES",
                "PRI" if (c.name == meta.handle_col or c.name in pri_cols) else "",
                dflt,
                "auto_increment" if c.auto_increment else "",
            ))
        return out

    @staticmethod
    def _index_descs(meta: TableMeta) -> list:
        """(non_unique, index_name, seq_in_index, column_name) rows."""
        out = []
        for idx in meta.indices:
            for seq, cn in enumerate(idx.col_names, 1):
                out.append((0 if idx.unique else 1, idx.name, seq, cn))
        return out

    def _bind_information_schema(self, node, rw) -> None:
        """information_schema memtables served from the catalog
        (ref: pkg/infoschema memtables + pkg/executor/infoschema_reader.go —
        the reference serves these from TiDB itself via kv.StoreType=TiDB;
        here they materialize per statement). Covered: TABLES, COLUMNS,
        STATISTICS, TIDB_INDEXES-shaped index rows ride in STATISTICS."""
        if isinstance(node, A.Join):
            self._bind_information_schema(node.left, rw)
            self._bind_information_schema(node.right, rw)
            return
        if not isinstance(node, A.TableName) or node.db.lower() != "information_schema":
            return
        from ..tools.dump import _type_sql
        from ..types import new_varchar

        kind = node.name.lower()
        S, I = new_varchar(64), new_longlong()

        def schema_of(name: str):
            if "." in name:
                db, short = name.split(".", 1)
                return db, short
            return "test", name
        if kind == "tables":
            names = ["table_schema", "table_name", "table_rows", "tidb_table_id"]
            fts = [S, S, I, I]
            rows = []
            for name in self.catalog.tables():
                m = self.catalog.table(name)
                db, short = schema_of(m.name)
                rows.append([Datum.string(db), Datum.string(short),
                             Datum.i64(m.row_count), Datum.i64(m.table_id)])
        elif kind == "columns":
            names = ["table_schema", "table_name", "column_name", "ordinal_position",
                     "column_type", "is_nullable", "column_key"]
            fts = [S, S, S, I, S, S, S]
            rows = []
            for name in self.catalog.tables():
                m = self.catalog.table(name)
                db, short = schema_of(m.name)
                for i, (cn, ctype, nullable, key, _, _) in enumerate(self._column_descs(m), 1):
                    rows.append([
                        Datum.string(db), Datum.string(short), Datum.string(cn),
                        Datum.i64(i), Datum.string(ctype),
                        Datum.string(nullable), Datum.string(key),
                    ])
        elif kind == "statistics":
            names = ["table_schema", "table_name", "non_unique", "index_name",
                     "seq_in_index", "column_name"]
            fts = [S, S, I, S, I, S]
            rows = []
            for name in self.catalog.tables():
                m = self.catalog.table(name)
                db, short = schema_of(m.name)
                for nu, iname, seq, cn in self._index_descs(m):
                    rows.append([
                        Datum.string(db), Datum.string(short),
                        Datum.i64(nu), Datum.string(iname),
                        Datum.i64(seq), Datum.string(cn),
                    ])
        elif kind == "slow_query":
            # ref: infoschema slow_query memtable fed by the slow log
            from ..types import new_double

            D = new_double()
            names = ["time", "query_time", "digest", "plan_digest", "query", "success", "error"]
            fts = [S, D, S, S, new_varchar(4096), I, new_varchar(1024)]
            rows = []
            import datetime as _dt

            for e in self.catalog.stmtlog.slow_entries():
                rows.append([
                    Datum.string(_dt.datetime.fromtimestamp(e.ts, _dt.timezone.utc).strftime("%Y-%m-%d %H:%M:%S")),
                    Datum.f64(e.duration_ms / 1e3),
                    Datum.string(e.digest), Datum.string(e.plan_digest),
                    Datum.string(e.sql),
                    Datum.i64(1 if e.success else 0),
                    Datum.string(e.error),
                ])
        elif kind == "statements_summary":
            # ref: pkg/util/stmtsummary -> information_schema.statements_summary
            from ..types import new_double

            D = new_double()
            names = ["digest", "digest_text", "exec_count", "sum_latency",
                     "max_latency", "avg_latency", "sum_rows", "errors",
                     "avg_device_ns", "max_device_ns", "avg_compile_ns",
                     "avg_backoff_ms", "avg_queue_ms", "cost_class", "sample_sql"]
            fts = [S, new_varchar(1024), I, D, D, D, I, I,
                   D, I, D, D, D, S, new_varchar(256)]
            rows = []

            for sm in self.catalog.stmtlog.summary_rows():
                n = sm.exec_count or 1
                rows.append([
                    Datum.string(sm.digest), Datum.string(sm.normalized),
                    Datum.i64(sm.exec_count), Datum.f64(sm.sum_latency_ms),
                    Datum.f64(sm.max_latency_ms), Datum.f64(sm.avg_latency_ms),
                    Datum.i64(sm.sum_rows), Datum.i64(sm.errors),
                    Datum.f64(sm.avg_device_ns), Datum.i64(sm.max_device_ns),
                    Datum.f64(sm.sum_compile_ns / n),
                    Datum.f64(sm.sum_backoff_ms / n),
                    Datum.f64(sm.sum_queue_ms / n),
                    Datum.string(topsql.COLLECTOR.cost_class(sm.digest)),
                    Datum.string(sm.sample_sql),
                ])
        elif kind == "tidb_top_sql":
            # ref: pkg/util/topsql/reporter — the windowed per-digest
            # resource ledger: top-K digests per metric per window plus
            # the "(others)" fold. Rows come straight from the collector's
            # ONE serializer (windows_view), the same snapshot
            # /topsql/api/v1/windows serves — the surfaces cannot drift
            from ..types import new_double

            D = new_double()
            names = ["window_start", "window_end", "live", "digest",
                     "plan_digest", "cost_class", "exec_count", "cpu_ns",
                     "device_ns", "compile_ns", "backoff_ms", "queue_ms",
                     "bytes_to_device", "cop_cache_hits", "plan_cache_hits",
                     "errors", "sample_sql"]
            fts = [D, D, I, S, S, S, I, I, I, I, D, D, I, I, I, I,
                   new_varchar(256)]
            rows = []
            for w in topsql.COLLECTOR.windows_view():
                digests = list(w["digests"])
                if w["others"] is not None:
                    digests.append(w["others"])
                for r in digests:
                    cls = ("" if r["digest"] == topsql.OTHERS_DIGEST
                           else topsql.COLLECTOR.cost_class(r["digest"]))
                    rows.append([
                        Datum.f64(w["start"]), Datum.f64(w["end"]),
                        Datum.i64(1 if w["live"] else 0),
                        Datum.string(r["digest"]), Datum.string(r["plan_digest"]),
                        Datum.string(cls), Datum.i64(r["exec_count"]),
                        Datum.i64(r["cpu_ns"]), Datum.i64(r["device_ns"]),
                        Datum.i64(r["compile_ns"]), Datum.f64(r["backoff_ms"]),
                        Datum.f64(r["queue_ms"]), Datum.i64(r["bytes_to_device"]),
                        Datum.i64(r["cop_cache_hits"]), Datum.i64(r["plan_cache_hits"]),
                        Datum.i64(r["errors"]), Datum.string(r["sample_sql"]),
                    ])
        else:
            raise SQLError(f"information_schema.{kind} not supported yet")
        meta = rw.registry.register(names, fts, rows)
        # db-scoped binding: the planner resolves information_schema.<name>
        # through this key only, so a user table named "tables" is untouched
        # and the AST stays reusable (prepared statements re-bind per run)
        rw.bindings[f"information_schema.{kind}"] = meta

    def _shadow_dirty_tables(self, node, rw) -> None:
        """Bind every txn-dirty table referenced in FROM to a materialized
        overlay (committed snapshot + this txn's buffered rows) — the
        UnionScan analog (ref: pkg/executor/union_scan.go; the reference
        likewise disables pushdown below a dirty table's reader)."""
        if isinstance(node, A.TableName):
            name = node.name.lower()
            if name in rw.bindings:
                return
            try:
                meta = self.catalog.table(name)
            except CatalogError:
                return
            ops = self.txn.row_ops.get(meta.table_id)
            if not ops:
                return
            rows = [row for _, row in self._scan_rows_with_handles(meta, None, self.txn.start_ts)]
            m = rw.registry.register([c.name for c in meta.columns], meta.fts(), rows)
            rw.bindings[name] = m
        elif isinstance(node, A.Join):
            self._shadow_dirty_tables(node.left, rw)
            self._shadow_dirty_tables(node.right, rw)

    def _select_for_update(self, stmt: A.SelectStmt) -> None:
        """SELECT ... FOR UPDATE: pessimistic locks on the matched probe
        rows (ref: PointGetExec / SelectLock executor lock-keys step)."""
        if self.txn is None or not self.txn.explicit:
            return  # autocommit SELECT FOR UPDATE locks nothing durable
        if not isinstance(stmt.from_clause, A.TableName):
            raise SQLError("SELECT ... FOR UPDATE supports single-table queries only")
        try:
            meta = self.catalog.table(stmt.from_clause.name)
        except CatalogError:
            return  # CTE/derived target: nothing lockable
        try:
            matched = self._scan_rows_with_handles(meta, stmt.where, self.txn.start_ts)
        except (PlanError, SQLError):
            # WHERE references rewrite markers the row scanner cannot
            # evaluate: lock the whole table (conservative, never unsound)
            matched = self._scan_rows_with_handles(meta, None, self.txn.start_ts)
        self._lock_rows(meta, [h for h, _ in matched])

    def _lookup_handle_ranges(self, plan, ts) -> list:
        """Phase 1 of the double-read: scan index entries over the pruned
        index key ranges, collect handles, coalesce consecutive handles
        into second-phase table ranges (batched + ordered — the keep_order
        analog of IndexLookUpExecutor's handle batching)."""
        from ..distsql import handle_ranges
        from ..exec.dag import IndexScan

        meta = plan.probe_table
        lookups = plan.lookup_merge if plan.lookup_merge else [plan.lookup]
        handles_set: set = set()
        for index_id, iranges in lookups:
            idx = next(i for i in meta.indices if i.index_id == index_id)
            vcols = [meta.col(cn) for cn in idx.col_names]
            icols = tuple(ColumnInfo(c.col_id, c.ft) for c in vcols) + (ColumnInfo(-1, HANDLE_FT),)
            hdag = DAGRequest(
                (IndexScan(meta.table_id, index_id, icols),),
                output_offsets=(len(icols) - 1,),
            )
            chunk = execute_root(self.store, hdag, iranges, start_ts=ts)
            handles_set |= {int(r[0].val) for r in chunk.rows()}
        handles = sorted(handles_set)
        pairs: list[list[int]] = []
        for h in handles:
            if pairs and h == pairs[-1][1] + 1:
                pairs[-1][1] = h
            else:
                pairs.append([h, h])
        return handle_ranges(meta.table_id, [(a, b) for a, b in pairs])

    def _select_via_oracle(self, plan, ranges, aux, ts) -> Chunk:
        from ..exec import run_dag_reference

        scan = plan.dag.executors[0]
        probe_dag = DAGRequest((scan,), output_offsets=tuple(range(len(scan.columns))))
        res = execute_root(self.store, probe_dag, ranges, start_ts=ts)
        rows = run_dag_reference(plan.dag, [res] + list(aux))
        return Chunk.from_rows(plan.dag.output_fts(), rows)

    def _fetch_table_chunk(self, meta: TableMeta, ts: int) -> Chunk:
        scan = TableScan(meta.table_id, meta.scan_columns())
        dag = DAGRequest((scan,), output_offsets=tuple(range(len(meta.columns))))
        ranges = [r for pid in meta.physical_ids() for r in full_table_ranges(pid)]
        return execute_root(self.store, dag, ranges, start_ts=ts)

    # ------------------------------------------------------------------
    def _eval_const(self, node: A.ExprNode, ft: FieldType) -> Datum:
        lw = _Lowerer(_Scope([]))
        ev = RefEvaluator()
        d = ev.eval(lw.lower_base(node), [])
        return _coerce_datum(d, ft)

    def _create_index(self, stmt: A.CreateIndexStmt) -> Result:
        """CREATE INDEX: a DDL job stepping the online states, then the
        write-reorg backfill (ref: pkg/ddl/index.go + backfilling.go —
        single process, so one synchronous pass)."""
        from .ddl import run_job

        meta = self.catalog.table(stmt.table.name)
        cols = [c[0] if isinstance(c, tuple) else str(c) for c in stmt.columns]
        n = run_job(self.catalog, "add index", meta.name,
                    f"CREATE INDEX {stmt.index_name} ON {meta.name}",
                    lambda step: self._build_index(meta, stmt.index_name, cols, stmt.unique, step=step),
                    index_states=True)
        return Result(affected=n)

    def _build_index(self, meta: TableMeta, index_name: str, cols: list, unique: bool, step=None) -> int:
        """ONLINE index build (shared by CREATE INDEX and ALTER ADD INDEX):
        the real F1 state walk (ref: pkg/ddl/index.go) — the IndexMeta's
        `state` drives concurrent DML's behavior at every step, not just a
        recorded list:

          delete_only   registered; DML honors deletes, adds no entries
          write_only    DML double-writes entries; readers still ignore it
          write_reorg   backfill scans a snapshot and writes every entry;
                        a verify pass tombstones entries whose row vanished
                        between the scan and the writes (concurrent DELETE)
          public        readers may use it

        `step` (from run_job) records each transition as a schema-version
        bump; failpoints let tests pause between states while writer
        threads run DML."""
        from ..util import failpoint

        step = step or (lambda st: None)
        im = self.catalog.add_index(meta.name, index_name, cols, unique, state="delete_only")
        try:
            step("delete_only")
            failpoint.eval("ddl_index_delete_only")
            im.state = "write_only"
            self.catalog.version += 1
            step("write_only")
            failpoint.eval("ddl_index_write_only")
            im.state = "write_reorg"
            self.catalog.version += 1
            step("write_reorg")
            failpoint.eval("ddl_index_write_reorg")
            ts = self._next_ts()
            rows = self._scan_rows_with_handles(meta, None, ts)
            wts = self._next_ts()
            pos = {c.name: i for i, c in enumerate(meta.columns)}
            # validate the WHOLE backfill before writing anything: a
            # duplicate found mid-write would leave dead index entries
            seen: dict = {}
            entries = []
            for handle, row in rows:
                vals = [row[pos[cn]] for cn in im.col_names]
                if im.unique and not any(d.is_null() for d in vals):
                    k = tuple(str(d) for d in vals)
                    if k in seen:
                        raise SQLError(f"duplicate entry for unique key {im.name!r} during backfill")
                    seen[k] = handle
                entries.append(tablecodec.encode_index_key(meta.table_id, im.index_id, vals + [Datum.i64(handle)]))
            for key in entries:
                self.store.put_index(key, b"\x00", wts)
            # verify pass: a row DELETEd between the scan snapshot and wts
            # would be resurrected by the backfill write — tombstone every
            # backfilled entry whose row no longer exists (ref: the
            # reference merges delete markers during reorg)
            vts = self._next_ts()
            live = set()
            for handle, row in self._scan_rows_with_handles(meta, None, vts):
                vals = [row[pos[cn]] for cn in im.col_names]
                live.add(tablecodec.encode_index_key(meta.table_id, im.index_id, vals + [Datum.i64(handle)]))
            dts = self._next_ts()
            for key in entries:
                if key not in live:
                    self.store.put_index(key, None, dts)
            im.state = "public"
            self.catalog.version += 1
            return len(rows)
        except Exception:
            self.catalog.drop_index(meta.name, im.name)  # roll back metadata
            raise

    def _drop_index(self, stmt: A.DropIndexStmt) -> Result:
        from .ddl import run_job

        meta = self.catalog.table(stmt.table.name)
        run_job(self.catalog, "drop index", meta.name,
                f"DROP INDEX {stmt.index_name} ON {meta.name}",
                lambda: self._drop_index_impl(meta, stmt.index_name))
        return Result()

    def _drop_index_impl(self, meta: TableMeta, index_name: str):
        """Catalog change through the locked/versioned path, then tombstone
        every entry of the dropped index (no KV leak)."""
        im = self.catalog.drop_index(meta.name, index_name)
        wts = self._next_ts()
        prefix = tablecodec.encode_index_key(meta.table_id, im.index_id, [])
        for key, _ in list(self.store.kv.scan(prefix, prefix + b"\xff", wts)):
            self.store.put_index(key, None, wts)

    def _scan_index_prefix(self, prefix: bytes, ts: int):
        """Live index keys under `prefix`: committed entries overlaid with
        this txn's buffered index mutations (tombstones hide, puts add)."""
        muts = self.txn.index_muts if self.txn is not None else {}
        _MISS = object()
        for key, _ in self.store.kv.scan(prefix, prefix + b"\xff", ts):
            if muts.get(key, _MISS) is None:
                continue  # tombstoned in this txn
            yield key
        for key, val in muts.items():
            # duplicate yields for keys also committed are harmless (the
            # caller checks handle ownership, not multiplicity)
            if val is not None and key.startswith(prefix):
                yield key

    def _find_unique_conflict(self, meta: TableMeta, datums: list, handle: int, ts: int, old_handle: int | None = None):
        """First (conflicting_handle, index) whose unique entry collides
        with this row, or None (ref: ER_DUP_ENTRY; MySQL allows multiple
        NULLs in a unique index). `old_handle` is the row's previous handle
        during a PK-changing UPDATE — its still-live entries are the row's
        own, not duplicates."""
        own = {handle, old_handle if old_handle is not None else handle}
        pos = {c.name: i for i, c in enumerate(meta.columns)}
        for idx in meta.indices:
            if idx.state == "delete_only":
                # not yet double-written: probing it would miss real rows;
                # pre-existing duplicates are caught by the reorg backfill
                continue
            if not idx.unique:
                continue
            vals = [datums[pos[cn]] for cn in idx.col_names]
            if any(d.is_null() for d in vals):
                continue
            prefix = tablecodec.encode_index_key(meta.table_id, idx.index_id, vals)
            for key in self._scan_index_prefix(prefix, ts):
                other = self._index_keys_handle(key)
                if other is not None and other not in own:
                    return other, idx
        return None

    def _check_unique(self, meta: TableMeta, datums: list, handle: int, ts: int, old_handle: int | None = None):
        conflict = self._find_unique_conflict(meta, datums, handle, ts, old_handle)
        if conflict is not None:
            raise SQLError(f"duplicate entry for unique key {conflict[1].name!r}")

    @staticmethod
    def _index_keys_handle(key: bytes) -> int | None:
        """Trailing handle datum of an index entry key."""
        from ..codec.datum_codec import decode_datums

        prefix_len = 1 + 8 + 2 + 8
        try:
            ds = decode_datums(key[prefix_len:])
            return int(ds[-1].val)
        except Exception:
            return None

    def _write_indexes(self, meta, datums, handle, delete=False):
        pos = {c.name: i for i, c in enumerate(meta.columns)}
        for idx in meta.indices:
            if not delete and idx.state == "delete_only":
                # F1 delete-only: concurrent DML removes entries but must
                # not ADD ones the backfill has not reached yet
                # (ref: pkg/ddl/index.go state semantics)
                continue
            vals = [datums[pos[cn]] for cn in idx.col_names] + [Datum.i64(handle)]
            key = tablecodec.encode_index_key(meta.table_id, idx.index_id, vals)
            val = None if delete else b"\x00"
            self.txn.mutations[key] = val
            self.txn.index_muts[key] = val

    def _insert(self, stmt: A.InsertStmt) -> Result:
        meta = self.catalog.table(stmt.table.name)
        ts = self.txn.start_ts
        if stmt.select is not None:
            src = self._select(stmt.select)
            cols = [c.lower() for c in (stmt.columns or [c.name for c in meta.columns])]
            rows = []
            for r in src.rows:
                if len(r) != len(cols):
                    raise SQLError("column count does not match value count")
                rows.append({cols[i]: d for i, d in enumerate(r)})
        else:
            cols = [c.lower() for c in (stmt.columns or [c.name for c in meta.columns])]
            rows = []
            for vals in stmt.values:
                if len(vals) != len(cols):
                    raise SQLError("column count does not match value count")
                # a DEFAULT literal behaves as if the column were omitted
                # (column default / generated recompute; ref: ast.Default
                # handling in executor/insert_common.go)
                rows.append({
                    cols[i]: self._eval_const(v, meta.col(cols[i]).ft)
                    for i, v in enumerate(vals)
                    if not isinstance(v, A.Default)
                })
        if stmt.on_duplicate:
            raise SQLError("ON DUPLICATE KEY UPDATE not supported yet")
        n = 0
        for r in rows:
            datums = []
            handle = None
            for c in meta.columns:
                if c.name in r:
                    if c.generated is not None:
                        # MySQL 3105: only DEFAULT may target a generated
                        # column (DEFAULT literals never land in `r`)
                        raise SQLError(
                            f"the value specified for generated column {c.name!r} "
                            f"in table {meta.name!r} is not allowed"
                        )
                    d = _coerce_datum(r[c.name], c.ft) if not isinstance(r[c.name], A.ExprNode) else r[c.name]
                else:
                    d = self._eval_const(c.default, c.ft) if c.default is not None else Datum.NULL
                if c.generated is not None:
                    d = Datum.NULL  # recomputed below, never user-supplied
                if meta.handle_col == c.name and not d.is_null():
                    handle = int(d.val)
                    meta.observe_handle(handle)
                datums.append(d)
            self._apply_generated(meta, datums)
            self._check_not_null(meta, datums)
            self._fk_check_child(meta, datums, ts)
            if handle is None:
                handle = meta.alloc_handle()
                if meta.handle_col is not None:
                    i = [c.name for c in meta.columns].index(meta.handle_col)
                    datums[i] = Datum.i64(handle)
            exists = self._read_row(meta, handle, ts) is not None
            if exists:
                # duplicate primary key (ref: ER_DUP_ENTRY / REPLACE / IGNORE)
                if stmt.ignore:
                    continue
                if not stmt.replace:
                    raise SQLError(f"duplicate entry {handle} for key PRIMARY")
            # secondary-unique conflicts: REPLACE deletes every conflicting
            # row; IGNORE skips the new row (ref: executor/replace.go
            # removeRow loop, insert IGNORE ER_DUP_ENTRY-as-warning)
            conflict = self._find_unique_conflict(meta, datums, handle, ts)
            if conflict is not None and stmt.ignore:
                continue
            if conflict is not None and not stmt.replace:
                raise SQLError(f"duplicate entry for unique key {conflict[1].name!r}")
            while conflict is not None:
                c_handle, _c_idx = conflict
                self._lock_rows(meta, [c_handle])
                old_row = self._read_row(meta, c_handle, ts)
                if old_row is not None:
                    self._write_indexes(meta, old_row, c_handle, delete=True)
                    self._buf_delete_row(meta, c_handle, old_row)
                    self.txn.row_delta[meta.table_id] = self.txn.row_delta.get(meta.table_id, 0) - 1
                    n += 1  # MySQL counts each replaced row
                conflict = self._find_unique_conflict(meta, datums, handle, ts)
            self._lock_rows(meta, [handle])
            if exists and stmt.replace and meta.indices:
                # REPLACE drops the old row's index entries; the old row is
                # fetched by its known key (no table scan)
                old_row = self._read_row(meta, handle, ts)
                if old_row is not None:
                    self._write_indexes(meta, old_row, handle, delete=True)
            self._buf_put_row(meta, handle, datums)
            self._write_indexes(meta, datums, handle)
            if not exists:
                n += 1
                self.txn.row_delta[meta.table_id] = self.txn.row_delta.get(meta.table_id, 0) + 1
            elif stmt.replace:
                n += 2  # replaced in place: MySQL counts delete AND insert
        return Result(affected=n)

    def _qualify_tables(self, stmt) -> None:
        qualify_tables_ast(stmt, self.db)

    # ------------------------------------------------ foreign keys
    def _fk_on(self) -> bool:
        return self.sysvars.get_bool("foreign_key_checks")

    def _fk_check_child(self, meta: TableMeta, datums: list, ts: int) -> None:
        """Referential check for an inserted/updated child row (ref:
        pkg/executor/foreign_key.go FKCheckExec on INSERT/UPDATE)."""
        if not self._fk_on() or not meta.foreign_keys:
            return
        pos = {c.name: i for i, c in enumerate(meta.columns)}
        for fk in meta.foreign_keys:
            vals = [datums[pos[c]] for c in fk.cols]
            if any(v.is_null() for v in vals):
                continue  # NULL components never violate (MATCH SIMPLE)
            try:
                parent = self.catalog.table(fk.ref_table)
            except CatalogError:
                continue
            if not self._fk_parent_exists(parent, fk.ref_cols, vals, ts):
                raise SQLError(
                    f"cannot add or update a child row: a foreign key "
                    f"constraint fails ({meta.name}.{fk.name})"
                )

    def _fk_parent_exists(self, parent: TableMeta, cols: list, vals: list, ts: int) -> bool:
        if (
            len(cols) == 1 and parent.handle_col == cols[0]
            and not vals[0].is_null()
        ):
            # referenced column IS the parent's int handle: point read
            # (ref: FK check via the reference's index/PK point lookup)
            try:
                return self._read_row(parent, int(vals[0].val), ts) is not None
            except (TypeError, ValueError):
                return False
        where = None
        for c, v in zip(cols, vals):
            e = A.BinaryOp("eq", A.ColumnName(c), A.Literal(v, "datum"))
            where = e if where is None else A.BinaryOp("and", where, e)
        return bool(self._scan_rows_with_handles(parent, where, ts, None, A.Limit(A.Literal(1, "int"))))

    def _fk_referencing(self, parent: TableMeta):
        """[(child_meta, FKMeta)] of every FK pointing at `parent` —
        memoized per schema version (DML loops ask once per row)."""
        cache = getattr(self, "_fk_ref_cache", None)
        if cache is None or cache[0] != self.catalog.version:
            refmap: dict = {}
            for name in self.catalog.tables():
                m = self.catalog.table(name)
                for fk in m.foreign_keys:
                    refmap.setdefault(fk.ref_table, []).append((m, fk))
            cache = (self.catalog.version, refmap)
            self._fk_ref_cache = cache
        return cache[1].get(parent.name, [])

    def _fk_on_parent_delete(self, meta: TableMeta, rows: list, ts: int, depth: int = 0) -> int:
        from ..exec.executor import datum_group_key  # noqa: PLC0415
        """RESTRICT / CASCADE / SET NULL on deleting parent rows (ref:
        pkg/executor/foreign_key.go FKCascadeExec). Returns cascaded-row
        count. `rows` are the parent row datum lists."""
        if not self._fk_on() or not rows:
            return 0
        if depth > 15:
            raise SQLError("foreign key cascade depth exceeded")
        n = 0
        for child, fk in self._fk_referencing(meta):
            ppos = {c.name: i for i, c in enumerate(meta.columns)}
            keysets = {
                tuple(datum_group_key(r[ppos[c]]) for c in fk.ref_cols)
                for r in rows
            }
            cpos = {c.name: i for i, c in enumerate(child.columns)}
            matched = [
                (h, r) for h, r in self._scan_rows_with_handles(child, None, ts)
                if not any(r[cpos[c]].is_null() for c in fk.cols)
                and tuple(datum_group_key(r[cpos[c]]) for c in fk.cols) in keysets
            ]
            if not matched:
                continue
            if fk.on_delete in ("restrict", "no_action"):
                raise SQLError(
                    f"cannot delete or update a parent row: a foreign key "
                    f"constraint fails ({child.name}.{fk.name})"
                )
            self._lock_rows(child, [h for h, _ in matched])
            if fk.on_delete == "cascade":
                n += self._fk_on_parent_delete(child, [r for _, r in matched], ts, depth + 1)
                for handle, row in matched:
                    self._buf_delete_row(child, handle, row)
                    self._write_indexes(child, row, handle, delete=True)
                self.txn.row_delta[child.table_id] = self.txn.row_delta.get(child.table_id, 0) - len(matched)
                n += len(matched)
            else:  # set_null
                for handle, row in matched:
                    new_row = list(row)
                    for c in fk.cols:
                        new_row[cpos[c]] = Datum.NULL
                    self._write_indexes(child, row, handle, delete=True)
                    self._buf_put_row(child, handle, new_row)
                    self._write_indexes(child, new_row, handle)
        return n

    def _fk_on_parent_update(self, meta: TableMeta, old_row: list, new_row: list, ts: int) -> None:
        """ON UPDATE actions when a referenced key changes (ref:
        executor/foreign_key.go onUpdate handling)."""
        if not self._fk_on():
            return
        from ..exec.executor import datum_group_key

        refs = self._fk_referencing(meta)
        if not refs:
            return
        ppos = {c.name: i for i, c in enumerate(meta.columns)}
        for child, fk in refs:
            old_key = tuple(datum_group_key(old_row[ppos[c]]) for c in fk.ref_cols)
            new_key = tuple(datum_group_key(new_row[ppos[c]]) for c in fk.ref_cols)
            if old_key == new_key:
                continue
            cpos = {c.name: i for i, c in enumerate(child.columns)}
            matched = [
                (h, r) for h, r in self._scan_rows_with_handles(child, None, ts)
                if not any(r[cpos[c]].is_null() for c in fk.cols)
                and tuple(datum_group_key(r[cpos[c]]) for c in fk.cols) == old_key
            ]
            if not matched:
                continue
            if fk.on_update in ("restrict", "no_action"):
                raise SQLError(
                    f"cannot delete or update a parent row: a foreign key "
                    f"constraint fails ({child.name}.{fk.name})"
                )
            self._lock_rows(child, [h for h, _ in matched])
            for handle, row in matched:
                nrow = list(row)
                for ci, pc in zip(fk.cols, fk.ref_cols):
                    nrow[cpos[ci]] = Datum.NULL if fk.on_update == "set_null" else new_row[ppos[pc]]
                self._write_indexes(child, row, handle, delete=True)
                self._buf_put_row(child, handle, nrow)
                self._write_indexes(child, nrow, handle)

    def _check_not_null(self, meta: TableMeta, datums: list) -> None:
        """NOT NULL (incl. implicit PK not-null) enforcement at write
        (ref: table/column.go CheckNotNull)."""
        from ..types import Flag

        for c, d in zip(meta.columns, datums):
            if d.is_null() and bool(c.ft.flag & Flag.NotNull) and not c.auto_increment \
                    and c.name != meta.handle_col:
                raise SQLError(f"column {c.name!r} cannot be null")

    def _apply_generated(self, meta: TableMeta, datums: list) -> None:
        """Materialize GENERATED ALWAYS AS columns from the row, in column
        order (later generated columns may reference earlier ones — the
        reference evaluates in dependency order, pkg/table/column.go
        CalcOnce ordering; column order subsumes it for valid schemas)."""
        if not any(c.generated is not None for c in meta.columns):
            return
        cached = getattr(meta, "_gen_cache", None)
        if cached is None or cached[0] != self.catalog.version:
            scope = _Scope([_TableRef(meta, meta.name.rsplit(".", 1)[-1], 0)])
            lw = _Lowerer(scope)
            prog = []
            for i, c in enumerate(meta.columns):
                if c.generated is not None:
                    prog.append((i, c, lw.lower_base(c.generated)))
            cached = (self.catalog.version, prog)
            meta._gen_cache = cached  # re-lowered per schema version only
        ev = RefEvaluator()
        for i, c, e in cached[1]:
            try:
                datums[i] = _coerce_datum(ev.eval(e, datums), c.ft)
            except SQLError:
                raise
            except Exception as exc:  # noqa: BLE001 — surface as SQL error
                raise SQLError(f"generated column {c.name!r}: {exc}") from exc

    def _read_row(self, meta: TableMeta, handle: int, ts: int) -> list | None:
        """Point read of one row by handle with txn-buffer overlay
        (ref: PointGet reading through the memdb first)."""
        from ..codec.rowcodec import decode_row_to_datum_map

        if self.txn is not None:
            ops = self.txn.row_ops.get(meta.table_id, {})
            if handle in ops:
                row = ops[handle]
                return list(row) if row is not None else None
        val = None
        if meta.partition is not None and meta.handle_col == meta.partition.col:
            # PK == partition column: the handle VALUE routes directly; a
            # value beyond the last RANGE bound simply has no row (MySQL
            # returns the empty set — the route() raise is for INSERT)
            from .catalog import CatalogError as _CE

            try:
                pid = meta.partition.route(handle)
            except _CE:
                return None
            val = self.store.kv.get(tablecodec.encode_row_key(pid, handle), ts)
        else:
            for pid in meta.physical_ids():
                val = self.store.kv.get(tablecodec.encode_row_key(pid, handle), ts)
                if val is not None:
                    break
        if val is None:
            return None
        dmap = decode_row_to_datum_map(val, {c.col_id: c.ft for c in meta.columns})
        return [
            fill_origin_default(val, c.col_id, c.origin_default, dmap[c.col_id])
            for c in meta.columns
        ]

    def _scan_rows_with_handles(self, meta: TableMeta, where: A.ExprNode | None, ts: int,
                                order_by: list | None = None, limit=None):
        """Row-level scan for UPDATE/DELETE: handles + full rows, filtered
        host-side with the reference evaluator (writes are not hot).
        order_by/limit implement `UPDATE/DELETE ... ORDER BY ... LIMIT n`."""
        scope = _Scope([_TableRef(meta, meta.name.rsplit(".", 1)[-1], 0)])
        lw = _Lowerer(scope)
        cond = lw.lower_base(where) if where is not None else None
        pinned = None
        if where is not None and meta.handle_col is not None:
            got = self._extract_pk_handles(
                meta, meta.name.rsplit(".", 1)[-1].lower(), where)
            if got is not None:
                pinned = got[0]
        if pinned is not None:
            # point-write fast path (ISSUE 19): WHERE pins the primary
            # key, so read exactly those rows instead of scanning the
            # table. _read_row already applies the txn overlay and
            # partition routing; the FULL where still evaluates below, so
            # filtering is byte-equivalent to the scan path.
            by_handle = {}
            for h in pinned:
                row = self._read_row(meta, h, ts)
                if row is not None:
                    by_handle[h] = list(row)
        else:
            cols = [ColumnInfo(-1, HANDLE_FT)] + list(meta.scan_columns())
            scan = TableScan(meta.table_id, tuple(cols))
            dag = DAGRequest((scan,), output_offsets=tuple(range(len(cols))))
            ranges = [r for pid in meta.physical_ids() for r in full_table_ranges(pid)]
            chunk = execute_root(self.store, dag, ranges, start_ts=ts)
            by_handle = {int(r[0].val): r[1:] for r in chunk.rows()}
            if self.txn is not None:
                # read-your-writes overlay (the UnionScan analog)
                for h, row in self.txn.row_ops.get(meta.table_id, {}).items():
                    if row is None:
                        by_handle.pop(h, None)
                    else:
                        by_handle[h] = list(row)
        ev = RefEvaluator()
        out = []
        for handle in sorted(by_handle):
            row = by_handle[handle]
            if cond is None or _truth(ev.eval(cond, row)):
                out.append((handle, row))
        if order_by:
            import functools

            from ..expr.eval_ref import compare

            items = [(lw.lower_base(b.expr), b.desc) for b in order_by]

            def cmp(a, b):
                for e, desc in items:
                    x, y = ev.eval(e, a[1]), ev.eval(e, b[1])
                    if x.is_null() and y.is_null():
                        continue
                    c = -1 if x.is_null() else (1 if y.is_null() else compare(x, y))
                    if c:
                        return -c if desc else c
                return 0

            out.sort(key=functools.cmp_to_key(cmp))
        if limit is not None:  # limit: A.Limit
            cnt = limit.count
            n = int(cnt.value) if isinstance(cnt, A.Literal) else int(cnt)
            out = out[:n]
        return out

    def _update(self, stmt: A.UpdateStmt) -> Result:
        if not isinstance(stmt.table, A.TableName):
            raise SQLError("multi-table UPDATE not supported")
        meta = self.catalog.table(stmt.table.name)
        ts = self.txn.start_ts
        matched = self._scan_rows_with_handles(meta, stmt.where, ts, stmt.order_by, stmt.limit)
        self._lock_rows(meta, [h for h, _ in matched])
        scope = _Scope([_TableRef(meta, meta.name.rsplit(".", 1)[-1], 0)])
        lw = _Lowerer(scope)
        col_pos = {c.name: i for i, c in enumerate(meta.columns)}
        assigns = []
        for a in stmt.assignments:
            cm = meta.col(a.column.name if isinstance(a.column, A.ColumnName) else str(a.column))
            if cm.generated is not None:
                raise SQLError(
                    f"the value specified for generated column {cm.name!r} "
                    f"in table {meta.name!r} is not allowed"
                )
            assigns.append((cm, lw.lower_base(a.expr)))
        ev = RefEvaluator()
        moves_handle = meta.handle_col is not None and any(cm.name == meta.handle_col for cm, _ in assigns)
        for handle, row in matched:
            new_row = list(row)
            for cm, e in assigns:
                # MySQL applies SET left-to-right over already-updated values
                new_row[col_pos[cm.name]] = _coerce_datum(ev.eval(e, new_row), cm.ft)
            self._apply_generated(meta, new_row)
            self._check_not_null(meta, new_row)
            self._fk_check_child(meta, new_row, ts)
            self._fk_on_parent_update(meta, row, new_row, ts)
            new_handle = handle
            if moves_handle:
                d = new_row[col_pos[meta.handle_col]]
                if d.is_null():
                    raise SQLError(f"column {meta.handle_col!r} cannot be NULL")
                new_handle = int(d.val)
            # ALL constraint checks before ANY mutation — a failed UPDATE
            # must not leave tombstoned index entries behind
            if new_handle != handle and self._read_row(meta, new_handle, ts) is not None:
                raise SQLError(f"duplicate entry {new_handle} for key PRIMARY")
            self._check_unique(meta, new_row, new_handle, ts, old_handle=handle)
            if new_handle != handle:
                # PK change moves the row to a new key (ref: updateRecord's
                # remove+add when the handle changes)
                self._buf_delete_row(meta, handle, row)
                self._lock_rows(meta, [new_handle])
            elif meta.partition is not None and meta.pid_for_row(row) != meta.pid_for_row(new_row):
                # partition-column change moves the row across partitions
                # (MySQL row movement): drop the old physical key
                self._buf_delete_row(meta, handle, row)
            self._write_indexes(meta, row, handle, delete=True)
            self._buf_put_row(meta, new_handle, new_row)
            self._write_indexes(meta, new_row, new_handle)
        return Result(affected=len(matched))

    def _delete(self, stmt: A.DeleteStmt) -> Result:
        if stmt.multi_table:
            raise SQLError("multi-table DELETE is not supported yet")
        meta = self.catalog.table(stmt.table.name)
        ts = self.txn.start_ts
        matched = self._scan_rows_with_handles(meta, stmt.where, ts, stmt.order_by, stmt.limit)
        self._lock_rows(meta, [h for h, _ in matched])
        self._fk_on_parent_delete(meta, [r for _, r in matched], ts)
        for handle, row in matched:
            self._buf_delete_row(meta, handle, row)
            self._write_indexes(meta, row, handle, delete=True)
        self.txn.row_delta[meta.table_id] = self.txn.row_delta.get(meta.table_id, 0) - len(matched)
        return Result(affected=len(matched))

    def _truncate(self, stmt) -> Result:
        meta = self.catalog.table(stmt.table.name)
        ts = self.txn.start_ts
        matched = self._scan_rows_with_handles(meta, None, ts)
        for handle, row in matched:
            self._buf_delete_row(meta, handle, row)
            self._write_indexes(meta, row, handle, delete=True)
        self.txn.row_delta[meta.table_id] = -meta.row_count
        return Result(affected=len(matched))

    def _analyze(self, stmt: A.AnalyzeTableStmt) -> Result:
        """ANALYZE TABLE: full-scan histogram/TopN/NDV build into the
        catalog's stats registry (ref: executor/analyze.go driving
        cophandler/analyze.go collection; exact rather than sampled since
        the whole column is in-process)."""
        from .stats import TableStats, build_column_stats

        self._implicit_commit()
        for t in stmt.tables:
            meta = self.catalog.table(t.name)
            ts = self.store.next_ts()
            rows = [row for _, row in self._scan_rows_with_handles(meta, None, ts)]
            tstats = TableStats(row_count=len(rows), version=ts)
            want = {c.lower() for c in stmt.columns} if stmt.columns else None
            if want is not None:
                unknown = want - {c.name for c in meta.columns}
                if unknown:
                    raise SQLError(f"unknown column {sorted(unknown)[0]!r} in ANALYZE of {meta.name!r}")
            for i, cm in enumerate(meta.columns):
                if want is not None and cm.name not in want:
                    continue
                tstats.columns[cm.name] = build_column_stats([r[i] for r in rows])
            self.catalog.stats[meta.table_id] = tstats
            meta.row_count = len(rows)  # ANALYZE also repairs the stat
        return Result()

    # ------------------------------------------------------------------
    def _session_tracker(self):
        """Per-session memory tracker: every query tracker parents here,
        so one session's concurrent + accumulated staging shares a quota
        (tidb_mem_quota_session; 0 = unlimited). The breach action spills
        the store's device-resident staging caches to host before the
        cancel fires — the util/memory.py action chain (ISSUE 15)."""
        from ..util.memory import MemTracker

        t = getattr(self, "_mem_tracker", None)
        if t is None:
            def _spill(tr, _n):
                from ..util import metrics

                self.store.evict_caches()
                metrics.MEM_EVICTIONS.inc()

            t = self._mem_tracker = MemTracker("session", action=_spill)
        q = self.sysvars.get_int("tidb_mem_quota_session")
        t.quota = q or None
        return t

    def _try_point_get(self, stmt: A.SelectStmt, rw) -> tuple | None:
        """PointGet/BatchPointGet fast path (ref: pkg/executor/point_get.go,
        batch_point_get.go; planner TryFastPlan): single real table, WHERE
        pins the integer primary key to constants -> read rows by key,
        bypassing distsql/coprocessor entirely. Split into shape DETECTION
        (shared with the plan cache's pointget tier) and EXECUTION."""
        det = self._point_get_detect(stmt, rw.mat_dict())
        if det is None:
            return None
        return self._exec_point_get(stmt, *det)

    def _point_get_detect(self, stmt: A.SelectStmt, mat) -> tuple | None:
        """Shape check + handle extraction: (meta, alias, handles, rest
        conjuncts) when the statement is the point-get shape, else None.
        Pure — reads the catalog but executes nothing."""
        if (
            not isinstance(stmt.from_clause, A.TableName)
            or stmt.group_by or stmt.having is not None or stmt.distinct
            or stmt.from_clause.name.lower() in mat
        ):
            return None
        try:
            meta = self.catalog.table(stmt.from_clause.name)
        except CatalogError:
            return None
        if meta.handle_col is None:
            return None
        alias = (stmt.from_clause.alias or meta.name).lower()
        pinned = self._extract_pk_handles(meta, alias, stmt.where)
        if pinned is None:
            return None
        handles, rest = pinned
        # any aggregate/window in the select list leaves the fast path
        from .planner import _has_agg, _has_window

        for f in stmt.fields:
            e = f.expr if isinstance(f, A.SelectField) else f
            if not isinstance(e, A.Star) and (_has_agg(e) or _has_window(e)):
                return None
        return meta, alias, handles, rest

    def _extract_pk_handles(self, meta: TableMeta, alias: str, where) -> tuple | None:
        """WHERE-clause handle extraction shared by the point-get fast
        path and the DML point-write tier (ISSUE 19): (pinned handles,
        residual conjuncts) when the conjuncts pin the integer primary
        key through eq/IN literals, else None. Pure — executes nothing."""
        from .planner import _lower_literal, _split_conjuncts

        conjs = _split_conjuncts(where)
        if any(isinstance(c, A.SemiJoinCond) for c in conjs):
            return None  # decorrelated subquery markers need the full planner
        handles: list | None = None
        rest: list = []
        for c in conjs:
            got = None
            if isinstance(c, A.BinaryOp) and c.op == "eq":
                for lhs, rhs in ((c.left, c.right), (c.right, c.left)):
                    if (
                        isinstance(lhs, A.ColumnName)
                        and lhs.name.lower() == meta.handle_col
                        and (not lhs.table or lhs.table.lower() == alias)
                        and isinstance(rhs, A.Literal) and rhs.kind in ("int", "datum")
                    ):
                        d = _lower_literal(rhs).datum
                        if not d.is_null() and isinstance(d.val, int):
                            got = [int(d.val)]
                        break
            elif (
                isinstance(c, A.InList) and not c.negated
                and isinstance(c.expr, A.ColumnName)
                and c.expr.name.lower() == meta.handle_col
                and (not c.expr.table or c.expr.table.lower() == alias)
                and all(isinstance(i, A.Literal) and i.kind in ("int", "datum") for i in c.items)
            ):
                ds = [_lower_literal(i).datum for i in c.items]
                if all(not d.is_null() and isinstance(d.val, int) for d in ds):
                    got = sorted({int(d.val) for d in ds})
            if got is not None:
                handles = got if handles is None else [h for h in handles if h in set(got)]
            else:
                rest.append(c)
        if handles is None:
            return None
        return handles, rest

    def _exec_point_get(self, stmt: A.SelectStmt, meta, alias, handles, rest) -> tuple:
        """Execute a detected point get: read the pinned handles, filter
        the residual conjuncts, evaluate the select list on the host.
        Plan-cache-hit statements (the _coalesce_hint window) first try
        the store's cross-session coalescer: concurrent point gets park
        briefly and ship as ONE batched device launch (ISSUE 19)."""
        by_handle = self._coalesce_point_get(meta, handles)
        if by_handle is not None:
            rows = [by_handle[h] for h in handles if h in by_handle]
        else:
            ts = self._pin_read_ts()
            try:
                rows = []
                for h in handles:
                    row = self._read_row(meta, h, ts)
                    if row is not None:
                        rows.append(row)
            finally:
                self._unpin_read_ts(ts)
        scope = _Scope([_TableRef(meta, alias, 0)])
        lw = _Lowerer(scope)
        ev = RefEvaluator()
        if rest:
            conds = [lw.lower_base(c) for c in rest]
            rows = [r for r in rows if all(_truth(ev.eval(c, r)) for c in conds)]
        fields = []
        for f in stmt.fields:
            e = f.expr if isinstance(f, A.SelectField) else f
            if isinstance(e, A.Star):
                fields.extend(A.SelectField(A.ColumnName(cm.name, alias), cm.name) for cm in meta.columns)
            else:
                fields.append(f)
        aliases = {f.alias.lower(): f.expr for f in fields if isinstance(f, A.SelectField) and f.alias}
        lw = _Lowerer(scope, aliases)
        exprs = [lw.lower_base(f.expr) for f in fields]
        out = [[ev.eval(e, r) for e in exprs] for r in rows]
        if stmt.order_by:
            import functools

            from ..expr.eval_ref import compare

            def positional(e):
                # ORDER BY 2 = select-list ordinal (matches the planner)
                if isinstance(e, A.Literal) and e.kind == "int":
                    i = int(e.value)
                    if not (1 <= i <= len(fields)):
                        raise SQLError(f"ORDER BY position {i} out of range")
                    return fields[i - 1].expr
                return e

            items = [(lw.lower_base(positional(b.expr)), b.desc) for b in stmt.order_by]
            # ORDER BY evaluates against the source row, so sort pairs
            paired = list(zip(rows, out))

            def cmp2(x, y):
                for e, desc in items:
                    a, b = ev.eval(e, x[0]), ev.eval(e, y[0])
                    if a.is_null() and b.is_null():
                        continue
                    c = -1 if a.is_null() else (1 if b.is_null() else compare(a, b))
                    if c:
                        return -c if desc else c
                return 0

            paired.sort(key=functools.cmp_to_key(cmp2))
            out = [o for _, o in paired]
        if stmt.limit is not None:
            def _n(e, dflt):
                if e is None:
                    return dflt
                if isinstance(e, A.Literal):
                    return int(e.value)
                return int(e)

            off = _n(stmt.limit.offset, 0)
            out = out[off : off + _n(stmt.limit.count, len(out))]
        from .planner import _field_label

        names = [_field_label(f) for f in fields]
        return names, [e.ft for e in exprs], out

    def _coalesce_point_get(self, meta: TableMeta, handles) -> dict | None:
        """Park this point get in the store's micro-batch window
        (ISSUE 19): {handle: row} on a coalesced read, None when the
        statement must take the single path — coalescing off, a session
        state that owns its own snapshot (txn, tidb_snapshot), or a
        value-routed (partitioned) table whose keys aren't
        handle-addressed. Window faults also return None: the coalescer
        reports the lane's fall-out and the single path re-reads."""
        coalescer = getattr(self.store, "coalescer", None)
        if (
            coalescer is None
            or not self._coalesce_hint
            or self.txn is not None
            or self.sysvars.get("tidb_snapshot")
            or meta.partition is not None
            or meta.table_id < 0
            or not self.sysvars.get_bool("tidb_tpu_enable_coalesce")
        ):
            return None
        return coalescer.point_get(
            meta, handles,
            tag=topsql.current_tag(),
            wait_us=self.sysvars.get_int("tidb_tpu_coalesce_wait_us"),
            max_lanes=self.sysvars.get_int("tidb_tpu_coalesce_max_lanes"),
        )

    def _load_stats_json(self, path: str) -> None:
        """Minimal LoadStatsFromJSON: count/NDV/null_count/TopN land in the
        stats registry (histogram bucket decode is format-versioned in the
        reference; NDV+TopN carry the planner decisions here)."""
        import json as _json

        from .stats import ColumnStats, TableStats

        blob = _json.load(open(path))
        meta = self.catalog.table(blob.get("table_name", "") or "")
        tstats = TableStats(row_count=int(blob.get("count", 0)), version=self.store.next_ts())
        for cn, cd in (blob.get("columns") or {}).items():
            hist = cd.get("histogram") or {}
            cs = ColumnStats(
                null_count=int(cd.get("null_count", 0)),
                ndv=int(hist.get("ndv", cd.get("distinct_count", 0) or 0)),
                total=int(blob.get("count", 0)) - int(cd.get("null_count", 0)),
            )
            tstats.columns[cn.lower()] = cs
        self.catalog.stats[meta.table_id] = tstats
        meta.row_count = tstats.row_count

    def _admin(self, stmt: A.AdminStmt) -> Result:
        """ADMIN SHOW DDL JOBS / CHECK TABLE (ref: pkg/executor/admin.go)."""
        if stmt.kind == "show_ddl_jobs":
            rows = []
            for j in reversed(self.catalog.ddl_jobs.view()):
                rows.append([
                    Datum.i64(j.job_id), Datum.string(j.job_type), Datum.string(j.table),
                    Datum.string(j.schema_state), Datum.string(j.state),
                    Datum.string(j.error or ""),
                ])
            return Result(
                columns=["JOB_ID", "JOB_TYPE", "TABLE", "SCHEMA_STATE", "STATE", "ERROR"],
                rows=rows,
            )
        if stmt.kind == "check_table":
            # index consistency check (ref: admin check table): every row's
            # index entries exist and no dangling entries remain
            for t in stmt.tables:
                meta = self.catalog.table(t.name)
                ts = self.store.next_ts()
                rows = self._scan_rows_with_handles(meta, None, ts)
                pos = {c.name: i for i, c in enumerate(meta.columns)}
                for idx in meta.indices:
                    if idx.state != "public":
                        continue  # a building index is legitimately partial
                    live = set()
                    for handle, row in rows:
                        vals = [row[pos[cn]] for cn in idx.col_names] + [Datum.i64(handle)]
                        key = tablecodec.encode_index_key(meta.table_id, idx.index_id, vals)
                        live.add(key)
                        if self.store.kv.get(key, ts) is None:
                            raise SQLError(
                                f"admin check: row {handle} missing from index {idx.name!r}"
                            )
                    prefix = tablecodec.encode_index_key(meta.table_id, idx.index_id, [])
                    for key, _ in self.store.kv.scan(prefix, prefix + b"\xff", ts):
                        if key not in live:
                            raise SQLError(f"admin check: dangling entry in index {idx.name!r}")
            return Result()
        return Result()

    # ------------------------------------------------------------------
    def _show(self, stmt) -> Result:
        kind = getattr(stmt, "kind", "")
        if kind in ("create_table", "create_view"):
            vm = self.catalog.view_of(stmt.table.name)
            if kind == "create_view" and vm is None:
                raise SQLError(f"unknown view {stmt.table.name!r}")
            if vm is not None:
                cols = f" ({', '.join(vm.columns)})" if vm.columns else ""
                vshort = vm.name.rsplit(".", 1)[-1]
                return Result(
                    columns=["View", "Create View"],
                    rows=[[Datum.string(vshort),
                           Datum.string(f"CREATE VIEW `{vshort}`{cols} AS {vm.select_sql}")]],
                )
            from .showddl import show_create_table

            meta = self.catalog.table(stmt.table.name)
            short = meta.name.rsplit(".", 1)[-1]
            return Result(
                columns=["Table", "Create Table"],
                rows=[[Datum.string(short), Datum.string(show_create_table(meta))]],
            )
        if kind == "columns":
            meta = self.catalog.table(stmt.table.name)
            rows = [
                [Datum.string(cn), Datum.string(ctype), Datum.string(nullable),
                 Datum.string(key), Datum.string(dflt), Datum.string(extra)]
                for cn, ctype, nullable, key, dflt, extra in self._column_descs(meta)
            ]
            return Result(columns=["Field", "Type", "Null", "Key", "Default", "Extra"], rows=rows)
        if kind == "index":
            meta = self.catalog.table(stmt.table.name)
            rows = [
                [Datum.string(meta.name), Datum.i64(nu), Datum.string(iname),
                 Datum.i64(seq), Datum.string(cn)]
                for nu, iname, seq, cn in self._index_descs(meta)
            ]
            return Result(columns=["Table", "Non_unique", "Key_name", "Seq_in_index", "Column_name"], rows=rows)
        if kind == "bindings":
            cols = ["Original_sql", "Bind_sql", "Default_db", "Status", "Source", "Sql_digest"]
            store = self.catalog.bindings if stmt.global_scope else self._session_bindings()
            rows = [
                [Datum.string(r["original"]), Datum.string(r["bind"]),
                 Datum.string(r.get("db", "")), Datum.string("enabled"),
                 Datum.string("manual"), Datum.string(d)]
                for d, r in store.items()
            ]
            return Result(columns=cols, rows=rows)
        if kind == "placement":
            # SHOW PLACEMENT (ref: executor/show_placement.go — the
            # reference lists placement policies; our placement unit is
            # the region->store map the PD schedules, so each region is a
            # target with its store binding and scheduling state)
            pd = getattr(self.store, "pd", None)
            if pd is None:
                return Result(columns=["Target", "Placement", "Scheduling_State"], rows=[])
            rows = []
            for st in pd.stores_view():
                rows.append([
                    Datum.string(f"STORE {st['store_id']}"),
                    Datum.string(
                        f"regions={st['region_count']} size={st['region_size']} "
                        f"keys={st['region_keys']} leaders={st.get('leader_count', 0)} "
                        f"peers={st.get('peer_count', 0)} "
                        f"safe_ts_lag={st.get('safe_ts_lag', 0)}"
                    ),
                    Datum.string(
                        f"hot_read={st['hot_read_regions']} hot_write={st['hot_write_regions']}"
                    ),
                ])
            for r in pd.regions_view():
                peers = ",".join(str(p) for p in r.get("peers", ()))
                rows.append([
                    Datum.string(f"REGION {r['region_id']}"),
                    Datum.string(
                        f"store={r['store']} leader={r.get('leader', r['store'])} "
                        f"peers=[{peers}] range=[{r['start_key'][:24]},"
                        f"{r['end_key'][:24]}) epoch={r['epoch']} "
                        f"size={r['approximate_size']} keys={r['approximate_keys']}"
                    ),
                    Datum.string(pd.scheduling_state(r["region_id"])),
                ])
            return Result(columns=["Target", "Placement", "Scheduling_State"], rows=rows)
        if kind == "columnar":
            # SHOW COLUMNAR TABLES (ISSUE 12; ref: information_schema
            # .tiflash_replica): one row per replicated table — feed
            # state, delta/stable layer sizes, and the applied
            # resolved-ts frontier the scan-readiness gate consults
            rows = []
            for v in self.store.columnar.views():
                if not _show_like(stmt, v["table"]):
                    continue
                rows.append([
                    Datum.string(v["table"]), Datum.string(v["state"]),
                    Datum.i64(v["pids"]), Datum.i64(v["delta_rows"]),
                    Datum.i64(v["stable_rows"]), Datum.i64(v["stable_chunks"]),
                    Datum.i64(v["applied_ts"]), Datum.i64(v["stable_ts"]),
                    Datum.i64(v["resolved_ts_lag"]), Datum.i64(v["compactions"]),
                ])
            return Result(
                columns=["Table", "State", "Pids", "Delta_rows", "Stable_rows",
                         "Stable_chunks", "Applied_ts", "Stable_ts",
                         "Resolved_lag", "Compactions"],
                rows=rows,
            )
        if kind == "changefeeds":
            # SHOW CHANGEFEEDS (ref: TiCDC `cli changefeed list`): one row
            # per feed with its state, frontier, and emission counts
            rows = []
            for v in self.store.cdc.views():
                if not _show_like(stmt, v["name"]):
                    continue
                rows.append([
                    Datum.string(v["name"]), Datum.string(v["state"]),
                    Datum.string(v["sink"]), Datum.i64(v["start_ts"]),
                    Datum.i64(v["checkpoint_ts"]), Datum.i64(v["resolved_lag"]),
                    Datum.i64(v["pending"]), Datum.i64(v["emitted"]),
                    Datum.i64(v["skipped"]), Datum.string(v["error"]),
                ])
            return Result(
                columns=["Changefeed", "State", "Sink", "Start_ts", "Checkpoint_ts",
                         "Resolved_lag", "Pending", "Emitted", "Skipped", "Error"],
                rows=rows,
            )
        if kind == "backup_logs":
            # SHOW BACKUP LOGS (ISSUE 20; ref: `br log status`): one row
            # per attached log backup with its durable checkpoint chain
            from ..br import log_backup_views

            rows = [
                [
                    Datum.string(v["destination"]), Datum.string(v["changefeed"]),
                    Datum.string(v["state"]), Datum.i64(v["start_ts"]),
                    Datum.i64(v["checkpoint_ts"]), Datum.i64(v["resolved_lag"]),
                    Datum.i64(v["segments"]), Datum.i64(v["events"]),
                ]
                for v in log_backup_views(self.store)
            ]
            return Result(
                columns=["Destination", "Changefeed", "State", "Start_ts",
                         "Checkpoint_ts", "Resolved_lag", "Segments", "Events"],
                rows=rows,
            )
        if kind == "status":
            from ..util import metrics

            rows = [
                [Datum.string(series), Datum.string(value)]
                for series, value in metrics.REGISTRY.sample_lines()
            ]
            return Result(columns=["Variable_name", "Value"], rows=rows)
        if kind == "tables":
            names = sorted(set(self.catalog.tables()) | set(self.catalog.view_names()))
            # current database only, short names (multi-db catalog keys
            # are "db.table"; the default db owns the unqualified keys)
            if self.db == "test":
                names = [t for t in names if "." not in t]
            else:
                pre = self.db + "."
                names = [t[len(pre):] for t in names if t.startswith(pre)]
            names = [t for t in names if _show_like(stmt, t)]
            hdr = f"Tables_in_{self.db}"
            pat = getattr(stmt, "pattern", None)
            if pat:
                hdr += f" ({pat})"
            return Result(columns=[hdr], rows=[[Datum.string(t)] for t in names])
        if kind == "databases":
            pat = getattr(stmt, "pattern", None)
            hdr = "Database" + (f" ({pat})" if pat else "")
            dbs = sorted({"information_schema"} | self.catalog.databases)
            dbs = [d for d in dbs if _show_like(stmt, d)]
            return Result(columns=[hdr], rows=[[Datum.string(d)] for d in dbs])
        if kind == "variables":
            return Result(
                columns=["Variable_name", "Value"],
                rows=[
                    [Datum.string(k), Datum.string(v)]
                    for k, v in self.sysvars.items()
                    if _show_like(stmt, k)
                ],
            )
        return Result()

    def _explain(self, stmt) -> Result:
        inner = stmt.target
        probe = self._take_probe()  # the INNER statement's digest probe
        if isinstance(inner, A.SelectStmt):
            bound = self._match_binding(inner)
            if bound is not None:
                inner = bound  # binding hints grafted on
        if not isinstance(inner, A.SelectStmt):
            return Result()
        import copy

        from .subquery import SubqueryError

        # plan-cache attribution (ISSUE 15 satellite): plain EXPLAIN shows
        # whether the shape is cacheable (typed decline reason otherwise);
        # EXPLAIN ANALYZE re-arms the probe so the run consults the cache
        # for real and reports hit/miss in its plan_cache row
        pc_line = None
        if (probe is not None and isinstance(inner, A.SelectStmt)
                and self.sysvars.get_bool("tidb_enable_plan_cache")):
            from .plancache import shape_decline

            r = shape_decline(inner, self, probe)
            pc_line = "plan_cache: cacheable" if r is None else f"plan_cache: decline({r})"
        analyze_ast = copy.deepcopy(inner) if getattr(stmt, "analyze", False) else None
        if (analyze_ast is not None and probe is not None
                and isinstance(inner, A.SelectStmt)):
            self._stmt_probe = probe
        rw = self._new_rewriter(None)
        try:
            rw.process_ctes(inner.ctes)
            inner.ctes = []
            if inner.from_clause is None:
                return Result(columns=["plan"], rows=[[Datum.string("constant select")]])
            rw.rewrite_select(inner)
            self._bind_information_schema(inner.from_clause, rw)
            plan = plan_select(
                inner, self.catalog, mat=rw.mat_dict(),
                enable_index_merge=self.sysvars.get_bool("tidb_enable_index_merge"),
            )
        except (SubqueryError, PlanError, CatalogError) as exc:
            raise SQLError(str(exc)) from exc
        from ..distsql import split_dag

        rp = split_dag(plan.dag)
        if analyze_ast is not None:
            return self._explain_analyze(analyze_ast, rp)
        lines = [f"access: {plan.access_path}"]
        lines += [f"push[{type(e).__name__}]" for e in rp.push_dag.executors]
        if rp.root_dag is not None:
            lines += [f"root[{type(e).__name__}]" for e in rp.root_dag.executors[1:]]
        if pc_line is not None:
            lines.append(pc_line)
        return Result(columns=["plan"], rows=[[Datum.string(s)] for s in lines])

    def _explain_analyze(self, analyze_ast, rp) -> Result:
        """EXPLAIN ANALYZE: run the query through the NORMAL select path (so
        the feature gate, txn dirty-table shadowing, and the memory quota
        all apply exactly as they would to the statement itself) while a
        sink collects the coprocessor exec summaries
        (ref: tipb.ExecutorExecutionSummary consumed at
        pkg/distsql/select_result.go:499; EXPLAIN ANALYZE columns in
        pkg/executor/explain.go)."""
        from ..exec.dag import executor_walk

        sink: list = []
        self._explain_sink = sink
        self._last_plan_cache = None
        try:
            _, _, out_rows = self._run_select(analyze_ast, None)
        finally:
            self._explain_sink = None
        # dict entries are batched-dispatch attribution riding the sink
        # alongside the per-task summary lists (distsql/root.py)
        batch_stats = [e for e in sink if isinstance(e, dict)]
        sink = [e for e in sink if not isinstance(e, dict)]
        names = [type(e).__name__ for e in executor_walk(rp.push_dag.executors)]
        rows_sum = [0] * len(names)
        time_ns = [0] * len(names)
        compile_ns = [0] * len(names)
        cache_hits = [0] * len(names)
        bytes_sum = [0] * len(names)
        for task_summaries in sink:
            for i, s in enumerate(task_summaries[: len(names)]):
                rows_sum[i] += s.num_produced_rows
                time_ns[i] += s.time_processed_ns
                compile_ns[i] += getattr(s, "time_compile_ns", 0)
                cache_hits[i] += 1 if getattr(s, "cache_hit", False) else 0
                bytes_sum[i] += getattr(s, "num_bytes", 0)
        out = []
        if sink:
            # compile/cache attribute the task's ONE fused program to every
            # executor it contains; cache prints hits/tasks (ref: the
            # cop_cache hit ratio in EXPLAIN ANALYZE's execution info)
            out += [[
                Datum.string(f"push[{n}]"), Datum.i64(rows_sum[i]), Datum.i64(len(sink)),
                Datum.string(f"{time_ns[i] / 1e6:.2f}ms"),
                Datum.string(f"{compile_ns[i] / 1e6:.2f}ms"),
                Datum.string(f"{cache_hits[i]}/{len(sink)}"),
                Datum.i64(bytes_sum[i]),
            ] for i, n in enumerate(names)]
        else:
            # oracle/materialized path: no coprocessor tasks ran
            out.append([Datum.string("(no coprocessor summaries: oracle or in-memory path)"),
                        Datum.NULL, Datum.i64(0), Datum.NULL, Datum.NULL, Datum.NULL, Datum.NULL])
        if rp.root_dag is not None:
            for e in rp.root_dag.executors[1:]:
                out.append([Datum.string(f"root[{type(e).__name__}]"), Datum.NULL, Datum.i64(1),
                            Datum.NULL, Datum.NULL, Datum.NULL, Datum.NULL])
        # radix-join attribution (ISSUE 13): partitions/rung from the
        # compiled plan, escapes = skew rows the escape hatch routed
        # through the general kernel, summed over the tasks that rode it
        rx_tasks = rx_esc = rx_parts = rx_rung = 0
        for task_summaries in sink:
            for s in task_summaries:
                if getattr(s, "radix_partitions", 0):
                    rx_tasks += 1
                    rx_parts = max(rx_parts, s.radix_partitions)
                    rx_rung = max(rx_rung, s.radix_rung)
                    rx_esc += s.radix_escapes
        if rx_tasks:
            out.append([Datum.string("join_radix"), Datum.i64(rx_parts),
                        Datum.i64(rx_tasks), Datum.NULL, Datum.NULL,
                        Datum.string(f"rung={rx_rung} escapes={rx_esc}"),
                        Datum.NULL])
        if batch_stats:
            # batched coprocessor attribution: rows=regions batch-served,
            # tasks=vmapped launches, cache column carries launches saved
            regions = sum(b.get("regions", 0) for b in batch_stats)
            batches = sum(b.get("batches", 0) for b in batch_stats)
            saved = sum(b.get("launches_saved", 0) for b in batch_stats)
            out.append([Datum.string("batch_cop"), Datum.i64(regions), Datum.i64(batches),
                        Datum.NULL, Datum.NULL, Datum.string(f"saved={saved}"), Datum.NULL])
            mesh_lanes = sum(b.get("mesh_lanes", 0) for b in batch_stats)
            if mesh_lanes:
                # mesh-tier attribution: rows=region lanes whose partial
                # states psum-merged ON DEVICE, tasks=shard_map launches —
                # the store answered ONE merged state per launch, so the
                # root merge saw `launches` rows instead of `lanes`
                mesh_batches = sum(b.get("mesh_batches", 0) for b in batch_stats)
                out.append([Datum.string("mesh_cop"), Datum.i64(mesh_lanes),
                            Datum.i64(mesh_batches), Datum.NULL, Datum.NULL,
                            Datum.string(f"merged={mesh_lanes}->{mesh_batches}"),
                            Datum.NULL])
        if self._last_plan_cache:
            # per-statement cache attribution (ISSUE 15 satellite): did
            # THIS run hit, miss, or decline — and why
            s, reason, tier = self._last_plan_cache
            detail = {"hit": f"hit({tier})", "miss": "miss",
                      "decline": f"decline({reason})", "off": "off"}.get(s, s)
            out.append([Datum.string("plan_cache"), Datum.NULL, Datum.i64(1),
                        Datum.NULL, Datum.NULL, Datum.string(detail), Datum.NULL])
        out.append([Datum.string("result"), Datum.i64(len(out_rows)), Datum.i64(1),
                    Datum.NULL, Datum.NULL, Datum.NULL, Datum.NULL])
        return Result(columns=["executor", "rows", "tasks", "time", "compile", "cache", "bytes"], rows=out)
