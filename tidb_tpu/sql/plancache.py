"""Digest-keyed plan cache — the session-tier front door (ref:
pkg/planner/core/plan_cache.go + pkg/parser/digester.go: the reference
caches physical plans per normalized-SQL digest so repeated OLTP
statements and PREPARE/EXECUTE skip parse+plan entirely; our ProgramCache
already dedups compiled kernels BELOW the planner — this layer closes the
gap above it).

Key = the literal-masked lexer digest (the same normalization that drives
the slow log / statement summary, util/stmtlog.py) + current db + the
literal KIND signature + a plan-relevant sysvar fingerprint + the
session-binding revision. Schema drift is a validation, not a key part:
each entry records a content fingerprint of every referenced table and is
dropped when the catalog moved under it (invalidation rides the existing
`Catalog.version` / `TableMeta.schema_version` bumps).

Value = a literal-slotted template at one of three tiers, strongest first:

  pointget  the statement is the PointGet fast-path shape: the bound
            template re-executes the key read directly — no parse, no
            planner, no coprocessor.
  dag       the planned physical DAG with literal SLOTS: every literal
            provably lands either in a Selection comparison (re-lowered
            in place on hit) or in the scan-range recipe (ranger re-runs
            over the bound conjuncts — TiDB's rebuildRange-at-EXECUTE);
            parse AND plan are skipped.
  ast       the parsed statement template only: literals re-bind into a
            deep copy and the planner re-runs — parse is skipped. The
            graceful tier for shapes whose literals fold into the plan
            (projection arithmetic, LIMIT offsets, partition pruning).

Slots are carried by `SlotInt`/`SlotStr` — int/str subclasses tagged with
their lexical slot ordinal, assigned from the parser's token offsets
(`A.Literal.pos`). They compare/hash equal to their plain values, so the
install-time planning pass runs unchanged while every place a literal
SURVIVES into the plan stays discoverable. A literal the planner folds
away (so a re-bound value could not take effect) fails the slot audit and
the entry degrades to the `ast` tier — soundness by construction.

Non-cacheable shapes decline with a typed reason (DDL, multi-statement,
subqueries, views, user variables, stale reads, open transactions, ...),
surfaced per statement in EXPLAIN [ANALYZE] and the
`tidb_tpu_plan_cache_declines_total{reason=}` counter.
"""

from __future__ import annotations

import copy
import threading
from collections import OrderedDict
from dataclasses import dataclass, field

from ..parser import ast as A
from ..parser.lexer import T, tokenize


class RebindError(ValueError):
    """A cached template could not re-bind the hot statement's literals
    (recipe produced no intervals, slot/kind drift, ...) — the caller
    treats the lookup as a miss and replans from scratch."""


# --------------------------------------------------------------- slot values

class SlotInt(int):
    """int tagged with its literal-slot ordinal; == / hash() follow the
    plain value so planning with a slotted template is planning with the
    real statement."""

    def __new__(cls, v: int, slot: int):
        o = super().__new__(cls, v)
        o.slot = slot
        return o

    def __deepcopy__(self, memo):
        return SlotInt(int(self), self.slot)


class SlotStr(str):
    """str twin of SlotInt (string literals and float/decimal literal
    TEXT — the parser keeps those as strings)."""

    def __new__(cls, v: str, slot: int):
        o = super().__new__(cls, v)
        o.slot = slot
        return o

    def __deepcopy__(self, memo):
        return SlotStr(str.__str__(self), self.slot)


def slot_of(v) -> int | None:
    return getattr(v, "slot", None) if isinstance(v, (SlotInt, SlotStr)) else None


# ------------------------------------------------------------- text probing

# literal kinds a slot may carry; anything else (hex blobs, X/B literals,
# adjacent-string concat) declines the statement — see the parser's pos
# sentinel convention (-1 untracked, -2 uncacheable shape)
_SLOT_KINDS = {"int": "i", "str": "s", "decimal": "d", "float": "f", "null": "n"}


@dataclass
class StmtProbe:
    """One statement's text-derived cache probe: the literal-masked digest
    plus the masked-token count the AST's slot collection must match.
    Built once per `Session.execute` from a single lexer pass (the same
    pass also feeds the slow log's digest, so the hot path lexes once).

    `slot_values`/`slot_kinds` are the masked tokens' literal values in
    lexical order — EXACTLY what the parser would store on the matching
    `A.Literal` nodes (ints parsed, decimal/float/string text verbatim;
    the parser never transforms a masked token's text, unary minus stays
    an enclosing UnaryOp node). A cache hit binds them into the template
    WITHOUT parsing — the parse-free fast path."""

    digest: str
    normalized: str
    n_masked: int
    has_var: bool = False
    multi_stmt: bool = False
    slot_values: tuple = ()
    slot_kinds: str = ""
    has_param: bool = False  # '?' markers: values come from EXECUTE, not text

    @staticmethod
    def from_sql(sql: str) -> "StmtProbe | None":
        try:
            toks = tokenize(sql)
        except Exception:  # noqa: BLE001 — unlexable text: no probe
            return None
        return StmtProbe._from_tokens(toks)

    @staticmethod
    def _from_tokens(toks) -> "StmtProbe":
        import hashlib

        parts = []
        values: list = []
        kinds: list = []
        has_var = False
        has_param = False
        multi = False
        last = len(toks) - 1
        for i, t in enumerate(toks):
            if t.kind is T.EOF:
                break
            if t.kind is T.NUMBER:
                parts.append("?")
                low = t.text.lower()
                if "e" in low:  # the parser's literal-kind decision, mirrored
                    values.append(t.text)
                    kinds.append("f")
                elif "." in t.text:
                    values.append(t.text)
                    kinds.append("d")
                else:
                    values.append(int(t.text))
                    kinds.append("i")
            elif t.kind is T.STRING:
                parts.append("?")
                values.append(t.text)
                kinds.append("s")
            elif t.kind is T.PARAM:
                # a PREPARE text's '?' markers are masked tokens too — the
                # prepared statement normalizes IDENTICALLY to its textual
                # form, so EXECUTE shares the direct statement's cache
                # entries and summary row (values bind at EXECUTE time)
                parts.append("?")
                values.append(None)
                kinds.append("?")
                has_param = True
            elif t.kind in (T.IDENT, T.QIDENT):
                parts.append(t.text.lower())
            else:
                if t.kind is T.OP and t.text == "@":
                    has_var = True
                if t.kind is T.OP and t.text == ";" and i < last - 1:
                    multi = True
                parts.append(t.text)
        norm = " ".join(parts)
        digest = hashlib.sha256(norm.encode()).hexdigest()[:32]
        return StmtProbe(digest, norm, len(values), has_var, multi,
                         tuple(values), "".join(kinds), has_param)

    @staticmethod
    def inner_probe(sql: str, kind: str) -> "StmtProbe | None":
        """Probe for the statement INSIDE an EXPLAIN [ANALYZE] / TRACE
        [FORMAT='x'] wrapper: strip the wrapper tokens and re-digest, so
        the inner statement shares cache entries with its direct form."""
        try:
            toks = tokenize(sql)
        except Exception:  # noqa: BLE001
            return None
        i = 0
        def at_kw(j, *kws):
            return (j < len(toks) and toks[j].kind is T.IDENT
                    and toks[j].text.lower() in kws)
        if kind == "explain":
            if not at_kw(i, "explain", "desc", "describe"):
                return None
            i += 1
            if at_kw(i, "analyze"):
                i += 1
        elif kind == "trace":
            if not at_kw(i, "trace"):
                return None
            i += 1
            if (at_kw(i, "format") and i + 2 < len(toks)
                    and toks[i + 1].text == "="):
                i += 3
        return StmtProbe._from_tokens(toks[i:])


# --------------------------------------------------------- slot collection

def collect_slots(stmt) -> list:
    """Token-position-tagged literals of a statement AST, in lexical
    order — the binding order of the masked tokens. Raises RebindError on
    an uncacheable literal shape (the parser's pos == -2 sentinel)."""
    out: list = []

    def walk(n):
        if isinstance(n, (list, tuple)):
            for x in n:
                walk(x)
            return
        if isinstance(n, A.Literal):
            if n.pos == -2:
                raise RebindError("uncacheable literal shape")
            if n.pos >= 0:
                out.append(n)
            return
        if isinstance(n, A.ParamMarker):
            raise RebindError("unbound parameter marker")
        if not hasattr(n, "__dataclass_fields__"):
            return
        for f_ in n.__dataclass_fields__:
            walk(getattr(n, f_))

    walk(stmt)
    out.sort(key=lambda lit: lit.pos)
    return out


def slot_signature(lits: list) -> str:
    sig = []
    for lit in lits:
        k = _SLOT_KINDS.get(lit.kind)
        if k is None:
            raise RebindError(f"uncacheable literal kind {lit.kind!r}")
        sig.append(k)
    return "".join(sig)


def wrap_slots(stmt, n_masked: int) -> str:
    """Tag the template's literals with their slot ordinals IN PLACE and
    return the kind signature. The count must match the lexer's masked
    tokens — a mismatch means some literal came from somewhere other than
    a masked token (string concat, synthesized nodes) and binding by
    position would be unsound."""
    lits = collect_slots(stmt)
    if len(lits) != n_masked:
        raise RebindError(
            f"literal slot count {len(lits)} != masked tokens {n_masked}")
    sig = slot_signature(lits)
    for i, lit in enumerate(lits):
        if lit.kind == "int":
            lit.value = SlotInt(int(lit.value), i)
        elif lit.kind in ("str", "decimal", "float"):
            lit.value = SlotStr(str(lit.value), i)
        # "null": value None is pinned by the kind signature — no tag
    return sig


def live_slot_values(stmt, n_masked: int) -> tuple[list, str]:
    """(values, kind signature) of the HOT statement's literals, by
    lexical position — what binds into a cached template."""
    lits = collect_slots(stmt)
    if len(lits) != n_masked:
        raise RebindError(
            f"literal slot count {len(lits)} != masked tokens {n_masked}")
    return [lit.value for lit in lits], slot_signature(lits)


def bind_template(template, values: list):
    """Clone a slotted template with the bound values substituted — the
    EXECUTE-parameter rebind, shared by every tier. One hand-rolled pass
    (clone + bind together): ASTs are trees of plain dataclasses, so a
    memo-free field walk beats copy.deepcopy by ~3x on the hit path;
    non-node leaves (ints, strings, Decimals, None) are immutable and
    pass through by reference."""

    def clone(n):
        if isinstance(n, A.Literal):
            s = slot_of(n.value)
            return A.Literal(values[s] if s is not None else n.value,
                             n.kind, n.pos)
        if isinstance(n, list):
            return [clone(x) for x in n]
        if isinstance(n, tuple):
            return tuple(clone(x) for x in n)
        fields_ = getattr(n, "__dataclass_fields__", None)
        if fields_ is None:
            return n
        out = object.__new__(type(n))
        for f_ in fields_:
            setattr(out, f_, clone(getattr(n, f_)))
        return out

    return clone(template)


# ------------------------------------------------------------ decline check

#: fixed reason vocabulary (metric label cardinality stays bounded)
DECLINE_REASONS = (
    "not_select", "ddl", "set_opr", "multi_statement", "user_var",
    "in_txn", "stale_read", "for_update", "cte", "subquery",
    "derived_table", "view", "memtable", "no_table", "literal_shape",
    "positional_ref", "uncacheable", "disabled", "dml_shape",
)

_DDL_KINDS = (
    "CreateTableStmt", "DropTableStmt", "AlterTableStmt", "RenameTableStmt",
    "CreateIndexStmt", "DropIndexStmt", "TruncateTableStmt",
    "CreateViewStmt", "DropViewStmt", "CreateDatabaseStmt",
    "DropDatabaseStmt",
)


def stmt_kind_reason(stmt) -> str | None:
    """Typed decline for statement kinds the cache never serves (None =
    SELECT — keep checking shape — or UPDATE/DELETE, whose point-write
    shapes get a `pointwrite` tier entry, ISSUE 19: the DML execute path
    owns that shape decision and counts `dml_shape` for the rest)."""
    if isinstance(stmt, (A.SelectStmt, A.UpdateStmt, A.DeleteStmt)):
        return None
    if isinstance(stmt, A.SetOprStmt):
        return "set_opr"
    if type(stmt).__name__ in _DDL_KINDS:
        return "ddl"
    return "not_select"


def shape_decline(stmt, session, probe: StmtProbe) -> str | None:
    """Typed reason this SELECT cannot be cached, or None. Session-state
    reasons (txn, stale read) are re-checked per statement; structural
    reasons transfer to every digest-equal statement."""
    if probe.multi_stmt:
        return "multi_statement"
    if probe.has_var:
        return "user_var"
    if session.txn is not None:
        return "in_txn"
    if session.sysvars.get("tidb_snapshot"):
        return "stale_read"
    if stmt.for_update:
        return "for_update"
    if stmt.ctes:
        return "cte"
    if stmt.from_clause is None:
        return "no_table"

    # FROM tree must be plain named tables (joins of TableNames)
    def from_ok(n):
        if isinstance(n, A.TableName):
            return True
        if isinstance(n, A.Join):
            return from_ok(n.left) and from_ok(n.right)
        return False

    if not from_ok(stmt.from_clause):
        return "derived_table"

    # any nested query anywhere (correlated state lives in the rewriter)
    found: list = []

    def walk(n, top=False):
        if isinstance(n, (list, tuple)):
            for x in n:
                walk(x)
            return
        if not top and isinstance(n, (A.SelectStmt, A.SetOprStmt, A.Exists)):
            found.append(n)
            return
        if not hasattr(n, "__dataclass_fields__"):
            return
        for f_ in n.__dataclass_fields__:
            walk(getattr(n, f_))

    walk(stmt, top=True)
    if found:
        return "subquery"

    names: list = []

    def tables(n):
        if isinstance(n, A.TableName):
            names.append(n)
        elif isinstance(n, A.Join):
            tables(n.left)
            tables(n.right)

    tables(stmt.from_clause)
    for t in names:
        eff_db = (t.db or session.db or "").lower()
        if eff_db in ("information_schema", "performance_schema"):
            return "memtable"
        if session.catalog.view_of(t.name) is not None:
            return "view"
        try:
            session.catalog.table(t.name)
        except Exception:  # noqa: BLE001 — unknown table: let the planner error
            return "uncacheable"
    return None


# --------------------------------------------------------------- table fps

def table_fingerprint(meta) -> tuple:
    """Content fingerprint of everything plan-relevant on a table: column
    shape, index set WITH online-DDL states, handle, partition layout.
    Any drift (ALTER TABLE, CREATE/DROP INDEX, reorg state steps)
    invalidates cached plans over the table."""
    return (
        meta.table_id, meta.schema_version,
        tuple((c.name, c.col_id, int(c.ft.tp), int(c.ft.flag), c.ft.flen,
               c.ft.decimal) for c in meta.columns),
        tuple((i.index_id, i.name, tuple(i.col_names), i.unique, i.state)
              for i in meta.indices),
        meta.handle_col,
        tuple(meta.physical_ids()),
    )


#: sysvars whose value shapes the PLAN (not just its execution): part of
#: the cache key, so a SET simply moves the session onto other entries
PLAN_SYSVARS = (
    "tidb_enable_tpu_coprocessor", "tidb_enable_tpu_mesh",
    "tidb_allow_mpp",
    "tidb_allow_batch_cop", "tidb_isolation_read_engines",
    "tidb_enable_index_merge", "sql_mode", "collation_connection",
    "time_zone", "div_precision_increment",
)


def sysvar_fingerprint(sysvars) -> str:
    return "|".join(sysvars.get(n) for n in PLAN_SYSVARS)


# ------------------------------------------------------------- cache entry

@dataclass
class PlanCacheEntry:
    tier: str  # "pointget" | "dag" | "ast"
    template: object  # slotted statement AST (never executed in place)
    n_slots: int
    kinds: str
    table_fps: dict  # catalog key name -> table_fingerprint
    catalog_version: int  # fast-path validation ticket; guarded by the cache lock
    bindings_rev: int
    has_limit: bool = False
    # dag tier only:
    plan: object = None  # slotted PlannedQuery
    range_src: tuple = ("full",)
    probe_name: str = ""
    build_names: tuple = ()
    hits: int = 0  # guarded by the cache lock


class PlanCache:
    """Server-shared LRU over (digest, db, kinds, sysvar-fp, bindings)
    keys — every session of a catalog consults one cache (the reference's
    instance-level plan cache)."""

    def __init__(self, capacity: int = 512, shared: bool = False):
        self.capacity = capacity
        #: the shared cross-catalog instance must not drive the
        #: tidb_tpu_plan_cache_entries gauge — that gauge tracks the
        #: per-catalog cache, and two writers would fight over it
        self._shared = shared
        self._mu = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # guarded_by: _mu

    def lookup(self, key, catalog, bindings_rev: int):
        """Validated entry for `key`, or None. Schema validation is a
        catalog.version ticket: unchanged version ⇒ tables unchanged;
        a moved version re-checks per-table content fingerprints and
        drops the entry on drift (the TableMeta.schema_version ride)."""
        from ..util import metrics

        with self._mu:
            e = self._entries.get(key)
            if e is None:
                return None
            self._entries.move_to_end(key)
            if e.bindings_rev != bindings_rev:
                del self._entries[key]
                if not self._shared:
                    metrics.PLAN_CACHE_ENTRIES.set(len(self._entries))
                return None
            if e.catalog_version != catalog.version:
                for name, fp in e.table_fps.items():
                    try:
                        meta = catalog.table(name)
                    except Exception:  # noqa: BLE001 — dropped table
                        meta = None
                    if meta is None or table_fingerprint(meta) != fp:
                        del self._entries[key]
                        if not self._shared:
                            metrics.PLAN_CACHE_ENTRIES.set(len(self._entries))
                        return None
                e.catalog_version = catalog.version  # re-validated: cheap again
            e.hits += 1
            return e

    def lookup_shared(self, key, catalog):
        """Cross-catalog lookup (ISSUE 19 satellite). A catalog.version
        ticket is meaningless in another catalog — two catalogs' version
        counters advance independently, so version 5 here and version 5
        there can name different schemas. Every shared hit therefore
        re-checks the per-table content fingerprints against the adopting
        catalog; the returned copy carries the adopter's version ticket so
        its promoted local entry validates cheaply from then on. Mismatch
        returns None without evicting — the entry stays valid for its
        home catalog."""
        with self._mu:
            e = self._entries.get(key)
            if e is None or e.bindings_rev != 0:
                return None
            self._entries.move_to_end(key)
            for name, fp in e.table_fps.items():
                try:
                    meta = catalog.table(name)
                except Exception:  # noqa: BLE001 — no such table here
                    meta = None
                if meta is None or table_fingerprint(meta) != fp:
                    return None
            e.hits += 1
            out = copy.copy(e)
            out.catalog_version = catalog.version
            return out

    def put(self, key, entry: PlanCacheEntry):
        from ..util import metrics

        with self._mu:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > max(self.capacity, 1):
                self._entries.popitem(last=False)
                if not self._shared:
                    metrics.PLAN_CACHE_EVICTIONS.inc()
            if not self._shared:
                metrics.PLAN_CACHE_ENTRIES.set(len(self._entries))

    def clear(self):
        from ..util import metrics

        with self._mu:
            self._entries.clear()
            if not self._shared:
                metrics.PLAN_CACHE_ENTRIES.set(0)

    def stats(self) -> dict:
        with self._mu:
            return {
                "entries": len(self._entries),
                "tiers": {t: sum(1 for e in self._entries.values() if e.tier == t)
                          for t in ("pointget", "dag", "ast", "pointwrite")},
            }

    def __len__(self):
        with self._mu:
            return len(self._entries)


# ----------------------------------------------- shared cross-catalog tier

#: process-wide tier behind every catalog's own cache (ISSUE 19
#: satellite): sessions over DIFFERENT catalogs (one TPUStore per tenant)
#: that compile the same digest against byte-identical schemas reuse one
#: slotted template instead of paying one compile per catalog. Entries
#: are copies — the home catalog's cache never aliases the shared one.
SHARED_CACHE = PlanCache(256, shared=True)


def publish_shared(key, entry: PlanCacheEntry,
                   catalog_bindings_rev: int, session_bindings_rev: int):
    """Offer a fresh install to the shared tier. Binding-active catalogs
    and sessions never publish (nor adopt): a binding-shaped plan must not
    leak into a catalog that doesn't carry that binding, and binding
    revisions don't transfer across catalogs."""
    if catalog_bindings_rev != 0 or session_bindings_rev != 0:
        return
    e = copy.copy(entry)
    e.hits = 0
    SHARED_CACHE.put(key, e)


# --------------------------------------------------------- dag-tier rebind

#: comparison ops whose DIRECT Const arguments may be literal slots — the
#: re-lowered const feeds a boolean, so no parent FieldType goes stale
_CMP_OPS = frozenset({"eq", "ne", "lt", "le", "gt", "ge", "nulleq", "in",
                      "between", "like"})
_LOGIC_OPS = frozenset({"and", "or", "not", "xor"})


def _relower(value, kind_code: str):
    """Re-lower a bound slot value exactly as a fresh parse+plan would
    (planner._lower_literal over the reconstructed literal)."""
    from .planner import _lower_literal

    kind = {"i": "int", "s": "str"}[kind_code]
    return _lower_literal(A.Literal(value, kind))


def audit_dag_slots(plan, kinds: str, n_slots: int) -> bool:
    """True when EVERY literal slot provably survives into a re-bindable
    position of the planned DAG: a Const that is a direct argument of a
    comparison inside a Selection (re-lowered on hit), or an int count on
    TopN/Limit. Slots the planner folded away, or that landed in
    projection/aggregation expressions (where parent FieldTypes were
    inferred from the cold value), fail the audit — the entry then rides
    the `ast` tier instead. Each surviving Const must also round-trip
    through re-lowering byte-identically, proving the hit-time rebind
    reproduces the cold plan exactly."""
    from ..expr.ir import Const, ScalarFunc
    from .dag_rebind import iter_exec_fields

    covered: set = set()
    ok = [True]

    def visit_expr(e, ctx):
        # ctx: "logic" (selection condition spine) | "other"
        if isinstance(e, Const):
            s = slot_of(e.datum.val)
            if s is None:
                return
            if ctx != "cmp":
                ok[0] = False
                return
            k = kinds[s]
            if k not in ("i", "s"):
                ok[0] = False
                return
            fresh = _relower(e.datum.val, k)
            if (fresh.datum.kind != e.datum.kind or fresh.datum.val != e.datum.val
                    or fresh.ft.tp != e.ft.tp or int(fresh.ft.flag) != int(e.ft.flag)
                    or fresh.ft.decimal != e.ft.decimal):
                ok[0] = False
                return
            covered.add(s)
            return
        if isinstance(e, ScalarFunc):
            if ctx == "logic" and e.op in _LOGIC_OPS:
                for a in e.args:
                    visit_expr(a, "logic")
                return
            if ctx == "logic" and e.op in _CMP_OPS:
                for a in e.args:
                    visit_expr(a, "cmp" if isinstance(a, Const) else "other")
                return
            for a in e.args:
                visit_expr(a, "other")

    from ..exec.dag import Limit, Selection, TopN

    for ex in plan.dag.executors:
        if isinstance(ex, Selection):
            for c in ex.conditions:
                visit_expr(c, "logic")
        elif isinstance(ex, (TopN, Limit)):
            s = slot_of(ex.limit)
            if s is not None:
                if kinds[s] != "i":
                    ok[0] = False
                else:
                    covered.add(s)
            for e, _k in iter_exec_fields(ex):
                visit_expr(e, "other")
        else:
            for e, _k in iter_exec_fields(ex):
                visit_expr(e, "other")
    if not ok[0]:
        return False
    # every slot must be re-bindable somewhere: a dag comparison const, a
    # TopN/Limit count, or a range-recipe conjunct (the recipe re-runs
    # ranger over the BOUND template WHERE, so slots that reached the
    # recipe's column are covered by construction when they also appear in
    # the Selection — which lowers EVERY local conjunct, consumed-by-range
    # or not). Anything else (folded, projected) fails.
    if slot_of(plan.offset) is not None:
        return False
    return covered | _null_slots(kinds) == set(range(n_slots))


def _null_slots(kinds: str) -> set:
    # NULL-kind slots (EXECUTE with a NULL parameter) are pinned by the
    # kind signature itself: every hit on this entry has NULL there
    return {i for i, k in enumerate(kinds) if k == "n"}


def rebind_plan(entry: PlanCacheEntry, values: list, catalog):
    """Bind hot literal values into a dag-tier entry → a fresh
    PlannedQuery: Consts re-lowered in place, scan ranges recomputed by
    the recipe (ranger re-run over the bound conjuncts — the
    rebuildRange-at-EXECUTE analog), table metas re-resolved live."""
    from dataclasses import replace as _dc_replace

    from .dag_rebind import rebind_dag
    from .planner import _split_conjuncts, range_const_of
    from .ranger import (
        handle_ranges_from_intervals,
        index_ranges_from_intervals,
        intervals_for_column,
    )

    plan = entry.plan

    def binder(slot: int):
        k = entry.kinds[slot]
        if k == "i":
            return _relower(int(values[slot]), "i")
        if k == "s":
            return _relower(str(values[slot]), "s")
        raise RebindError(f"slot {slot} kind {k!r} not dag-bindable")

    dag = rebind_dag(plan.dag, binder, values)
    try:
        probe_meta = catalog.table(entry.probe_name)
        builds = [catalog.table(n) for n in entry.build_names]
    except Exception as exc:  # noqa: BLE001 — table dropped between
        raise RebindError(str(exc)) from exc  # validation and bind

    ranges = plan.ranges
    lookup = plan.lookup
    src = entry.range_src
    if src[0] != "full":
        bound_tpl = bind_template(entry.template, values)
        conjs = [c for c in _split_conjuncts(bound_tpl.where)
                 if not isinstance(c, A.SemiJoinCond)]
        col_name = src[2] if len(src) > 2 else src[1]
        cm = probe_meta.col(col_name)
        ivs = intervals_for_column(conjs, cm.name, range_const_of(cm.ft))
        if ivs is None:
            raise RebindError(f"recipe produced no intervals for {col_name!r}")
        if src[0] == "handle":
            ranges = handle_ranges_from_intervals(probe_meta.table_id, ivs)
        elif src[0] == "index":
            ranges = index_ranges_from_intervals(probe_meta.table_id, src[1], ivs)
        elif src[0] == "lookup":
            lookup = (src[1],
                      index_ranges_from_intervals(probe_meta.table_id, src[1], ivs))
            ranges = None
        else:
            raise RebindError(f"unknown range recipe {src[0]!r}")
    return _dc_replace(plan, dag=dag, ranges=ranges, lookup=lookup,
                       probe_table=probe_meta, build_tables=builds)
