"""Privilege management (ref: pkg/privilege/privileges — MySQL-compatible
user records with global/db/table scoped privilege sets, cached in memory
exactly like the reference's MySQLPrivilege cache of the mysql.* tables).

The store lives on the shared Catalog (domain-level in the reference);
every session carries the authenticated user and execute_stmt checks the
statement's required privilege against it. The built-in 'root' user is a
superuser. Passwords are stored plain here and handed to the wire server,
which performs the mysql_native_password scramble check."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

PRIVS = frozenset({
    "select", "insert", "update", "delete", "create", "drop", "alter",
    "index", "all",
})


class PrivilegeError(ValueError):
    pass


@dataclass
class UserRecord:
    name: str
    host: str
    password: str = ""
    global_privs: set = field(default_factory=set)
    db_privs: dict = field(default_factory=dict)  # db -> set
    table_privs: dict = field(default_factory=dict)  # (db, table) -> set


class PrivilegeStore:
    def __init__(self):
        self._users: dict[tuple, UserRecord] = {}  # guarded_by: _lock
        self._lock = threading.Lock()
        # bootstrap superuser (ref: session/bootstrap.go root creation)
        self._users[("root", "%")] = UserRecord("root", "%", "", {"all"})

    # ------------------------------------------------------------------
    def create_user(self, name: str, host: str, password: str, if_not_exists: bool):
        with self._lock:
            key = (name.lower(), host)
            if key in self._users:
                if if_not_exists:
                    return
                raise PrivilegeError(f"user {name!r}@{host!r} already exists")
            self._users[key] = UserRecord(name.lower(), host, password or "")

    def drop_user(self, name: str, host: str, if_exists: bool):
        with self._lock:
            key = (name.lower(), host)
            if key not in self._users:
                if if_exists:
                    return
                raise PrivilegeError(f"user {name!r}@{host!r} does not exist")
            if key == ("root", "%"):
                raise PrivilegeError("cannot drop the bootstrap superuser")
            del self._users[key]

    def _record(self, name: str, host: str = "%") -> UserRecord:  # requires: _lock
        u = self._users.get((name.lower(), host)) or self._users.get((name.lower(), "%"))
        if u is None:
            raise PrivilegeError(f"user {name!r} does not exist")
        return u

    def grant(self, privs: list, db: str, table: str, name: str, host: str):
        with self._lock:
            u = self._record(name, host)
            pset = {p.lower() for p in privs}
            bad = pset - PRIVS
            if bad:
                raise PrivilegeError(f"unknown privilege {sorted(bad)[0]!r}")
            if db == "*" and table == "*":
                u.global_privs |= pset
            elif table == "*":
                u.db_privs.setdefault(db.lower(), set()).update(pset)
            else:
                u.table_privs.setdefault((db.lower(), table.lower()), set()).update(pset)

    def revoke(self, privs: list, db: str, table: str, name: str, host: str):
        with self._lock:
            u = self._record(name, host)
            pset = {p.lower() for p in privs}
            bad = pset - PRIVS
            if bad:
                raise PrivilegeError(f"unknown privilege {sorted(bad)[0]!r}")
            if db == "*" and table == "*":
                u.global_privs -= pset
            elif table == "*":
                u.db_privs.get(db.lower(), set()).difference_update(pset)
            else:
                u.table_privs.get((db.lower(), table.lower()), set()).difference_update(pset)

    # ------------------------------------------------------------------
    def check(self, user: str, priv: str, table: str = "*", db: str = "*") -> bool:
        """(ref: privileges.RequestVerification): global, then db, then
        table scope; 'all' matches any privilege. db defaults to the single
        implicit database, so db-qualified grants match unqualified use."""
        with self._lock:
            return self._check_locked(user, priv, table, db)

    def _check_locked(self, user: str, priv: str, table: str, db: str) -> bool:
        try:
            u = self._record(user)
        except PrivilegeError:
            return False
        want = {priv.lower(), "all"}
        if u.global_privs & want:
            return True
        if u.db_privs.get(db.lower(), set()) & want:
            return True
        if u.table_privs.get((db.lower(), table.lower()), set()) & want:
            return True
        # db-scope grant covers its tables; table grants under "*" db match
        if table != "*" and u.table_privs.get(("*", table.lower()), set()) & want:
            return True
        return False

    def is_super(self, user: str) -> bool:
        with self._lock:
            try:
                return "all" in self._record(user).global_privs
            except PrivilegeError:
                return False

    def password_of(self, user: str) -> bytes | None:
        """For the wire server's scramble check; None = unknown user."""
        with self._lock:
            try:
                return self._record(user).password.encode()
            except PrivilegeError:
                return None

    def users(self) -> list:
        with self._lock:
            return sorted(self._users)
