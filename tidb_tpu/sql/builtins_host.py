"""Host-evaluated builtin batch — the long tail of MySQL scalar functions
(ref: pkg/expression/builtin_string.go, builtin_encryption.go,
builtin_math.go). These are rarely hot-path: the reference evaluates them
row-wise too, and most sit outside every coprocessor pushdown whitelist,
so they register through the SAME extension mechanism user functions use
(sql/extension.py) and the DAG splitter pins them to the root oracle.

Registered once at import; names deliberately stay out of the device
compiler's SCALAR_OPS."""

from __future__ import annotations

import base64
import binascii
import hashlib
import math
import random
import uuid as _uuid
import zlib

from ..types import new_double, new_longlong, new_varchar
from .extension import EXTENSIONS

_NULL_IF_ANY = object()


def _as_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, bytearray):
        return bytes(v)
    return str(v).encode("utf-8")


def _as_str(v) -> str:
    return v.decode("utf-8", "replace") if isinstance(v, (bytes, bytearray)) else str(v)


def _as_num(v):
    if isinstance(v, (int, float)):
        return v
    try:
        return int(str(v))
    except ValueError:
        try:
            return float(str(v))
        except ValueError:
            return 0


def _hex(v):
    if v is None:
        return None
    if isinstance(v, int):
        return format(v, "X")
    if isinstance(v, float):
        return format(int(round(v)), "X")
    return _as_bytes(v).hex().upper()


def _unhex(v):
    if v is None:
        return None
    try:
        s = _as_str(v)
        if len(s) % 2:
            s = "0" + s
        return binascii.unhexlify(s)
    except (binascii.Error, ValueError):
        return None


def _sha2(v, bits):
    if v is None or bits is None:
        return None
    algo = {0: "sha256", 224: "sha224", 256: "sha256", 384: "sha384", 512: "sha512"}.get(int(bits))
    if algo is None:
        return None
    return getattr(hashlib, algo)(_as_bytes(v)).hexdigest()


# @@block_encryption_mode (ref: builtin_encryption.go deriveKeyMySQL +
# mode dispatch). Module-level because extension builtins get plain
# values; Session.__init__ resets it and SET updates it.
BLOCK_ENCRYPTION_MODE = "aes-128-ecb"


def _aes_mode(iv):
    """-> (key_size, mode_factory) per @@block_encryption_mode; ECB ignores
    the iv argument (MySQL warns), CBC/OFB/CFB require a 16-byte iv."""
    try:
        from cryptography.hazmat.primitives.ciphers import modes  # type: ignore
    except ImportError:
        return None
    parts = BLOCK_ENCRYPTION_MODE.lower().split("-")
    bits = int(parts[1]) if len(parts) == 3 and parts[1].isdigit() else 128
    mname = parts[2] if len(parts) == 3 else "ecb"
    if mname == "ecb":
        return bits // 8, modes.ECB(), False
    if iv is None or len(_as_bytes(iv)) < 16:
        raise ValueError("Incorrect initialization vector")
    ivb = _as_bytes(iv)[:16]
    if mname == "cbc":
        return bits // 8, modes.CBC(ivb), False
    # OFB/CFB are STREAM modes: no PKCS padding, any ciphertext length
    fac = {"ofb": modes.OFB, "cfb": getattr(modes, "CFB128", modes.CFB)}.get(mname)
    if fac is None:
        return bits // 8, modes.ECB(), False
    return bits // 8, fac(ivb), True


def _mysql_aes_key(key: bytes, size: int = 16) -> bytes:
    out = bytearray(size)
    for i, b in enumerate(key):
        out[i % size] ^= b
    return bytes(out)


def _aes_encrypt(v, key, iv=None):
    if v is None or key is None:
        return None
    try:
        from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes  # type: ignore
    except ImportError:
        return None  # no AES backend in this image: NULL like a bad key
    data = _as_bytes(v)
    try:
        ks, mode, stream = _aes_mode(iv)
    except ValueError:
        return None
    if not stream:
        pad = 16 - len(data) % 16
        data += bytes([pad]) * pad
    enc = Cipher(algorithms.AES(_mysql_aes_key(_as_bytes(key), ks)), mode).encryptor()
    return enc.update(data) + enc.finalize()


def _aes_decrypt(v, key, iv=None):
    if v is None or key is None:
        return None
    try:
        from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes  # type: ignore
    except ImportError:
        return None
    raw = _as_bytes(v)
    try:
        ks, mode, stream = _aes_mode(iv)
    except ValueError:
        return None
    if not raw or (not stream and len(raw) % 16):
        return None
    dec = Cipher(algorithms.AES(_mysql_aes_key(_as_bytes(key), ks)), mode).decryptor()
    try:
        out = dec.update(raw) + dec.finalize()
        if stream:
            return out
        pad = out[-1]
        if not 1 <= pad <= 16:
            return None
        return out[:-pad]
    except ValueError:
        return None


def _elt(n, *items):
    if n is None:
        return None
    i = int(_as_num(n))
    if i < 1 or i > len(items):
        return None
    return items[i - 1]


def _cmp_many(fn, args):
    if any(a is None for a in args):
        return None
    if all(isinstance(a, (int, float)) for a in args):
        return fn(args)
    try:
        nums = [float(_as_num(a)) for a in args]
        if any(isinstance(a, (bytes, str)) and not str(a).replace(".", "").replace("-", "").isdigit() for a in args):
            raise ValueError
        return fn(nums)
    except ValueError:
        return fn([_as_str(a) for a in args])


def _truncate(x, d):
    if x is None or d is None:
        return None
    d = int(_as_num(d))
    f = 10.0 ** d
    v = _as_num(x)
    out = math.floor(abs(v) * f) / f * (1 if v >= 0 else -1)
    if isinstance(v, int) and d >= 0:
        return int(out)
    return out


def _insert_fn(s, pos, ln, new):
    if s is None or pos is None or ln is None or new is None:
        return None
    if isinstance(s, (bytes, bytearray)) or isinstance(new, (bytes, bytearray)):
        # a binary operand makes the whole expression binary (byte units;
        # ref: builtin_string.go INSERT with binary collation)
        s, new = _as_bytes(s), _as_bytes(new)
    else:
        s, new = _as_str(s), _as_str(new)
    pos, ln = int(_as_num(pos)), int(_as_num(ln))
    if pos < 1 or pos > len(s):
        return s
    if ln < 0 or pos + ln - 1 >= len(s):
        return s[: pos - 1] + new
    return s[: pos - 1] + new + s[pos - 1 + ln :]


def _pad(s, ln, p, left: bool):
    if s is None or ln is None or p is None:
        return None
    if isinstance(s, (bytes, bytearray)) or isinstance(p, (bytes, bytearray)):
        s, p = _as_bytes(s), _as_bytes(p)
    else:
        s, p = _as_str(s), _as_str(p)
    ln = int(_as_num(ln))
    if ln < 0:
        return None
    if len(s) >= ln:
        return s[:ln]
    if not p:
        return None
    fill = (p * ln)[: ln - len(s)]
    return fill + s if left else s + fill


def _concat_ws(sep, *args):
    if sep is None:
        return None
    return _as_str(sep).join(_as_str(a) for a in args if a is not None)


def _compress(v):
    if v is None:
        return None
    data = _as_bytes(v)
    if not data:
        return b""
    import struct

    return struct.pack("<I", len(data)) + zlib.compress(data)


def _uncompress(v):
    if v is None:
        return None
    raw = _as_bytes(v)
    if not raw:
        return b""
    try:
        return zlib.decompress(raw[4:])
    except zlib.error:
        return None


def _microsecond(t):
    if t is None:
        return None
    s = _as_str(t)
    if "." in s:
        frac = s.rsplit(".", 1)[1][:6]
        return int(frac.ljust(6, "0"))
    return 0


def _password(v):
    if v is None:
        return None
    h = hashlib.sha1(hashlib.sha1(_as_bytes(v)).digest()).hexdigest().upper()
    return "*" + h


_DEFS = [
    ("hex", _hex, new_varchar()),
    ("unhex", _unhex, new_varchar()),
    ("md5", lambda v: None if v is None else hashlib.md5(_as_bytes(v)).hexdigest(), new_varchar(32)),
    ("sha", lambda v: None if v is None else hashlib.sha1(_as_bytes(v)).hexdigest(), new_varchar(40)),
    ("sha1", lambda v: None if v is None else hashlib.sha1(_as_bytes(v)).hexdigest(), new_varchar(40)),
    ("sha2", _sha2, new_varchar(128)),
    ("aes_encrypt", _aes_encrypt, new_varchar()),
    ("aes_decrypt", _aes_decrypt, new_varchar()),
    ("elt", _elt, new_varchar()),
    ("greatest", lambda *a: _cmp_many(max, a), new_varchar()),
    ("least", lambda *a: _cmp_many(min, a), new_varchar()),
    ("uuid", lambda: str(_uuid.uuid4()), new_varchar(36)),
    ("truncate", _truncate, new_double()),
    ("insert", _insert_fn, new_varchar()),
    ("lpad", lambda s, n, p: _pad(s, n, p, True), new_varchar()),
    ("rpad", lambda s, n, p: _pad(s, n, p, False), new_varchar()),
    ("concat_ws", _concat_ws, new_varchar()),
    ("pi", lambda: 3.141593, new_double()),
    ("ascii", lambda v: None if v is None else (ord(_as_str(v)[0]) if _as_str(v) else 0), new_longlong()),
    ("ord", lambda v: None if v is None else (_as_bytes(v)[0] if _as_bytes(v) else 0), new_longlong()),
    ("octet_length", lambda v: None if v is None else len(_as_bytes(v)), new_longlong()),
    ("to_base64", lambda v: None if v is None else base64.b64encode(_as_bytes(v)).decode(), new_varchar()),
    ("from_base64", lambda v: None if v is None else base64.b64decode(_as_bytes(v), validate=False), new_varchar()),
    ("compress", _compress, new_varchar()),
    ("uncompress", _uncompress, new_varchar()),
    ("instr", lambda s, sub: None if s is None or sub is None else _as_str(s).find(_as_str(sub)) + 1, new_longlong()),
    ("crc32", lambda v: None if v is None else zlib.crc32(_as_bytes(v)), new_longlong()),
    ("rand", lambda *a: random.Random(int(_as_num(a[0]))).random() if a and a[0] is not None else random.random(), new_double()),
    ("password", _password, new_varchar(41)),
    ("microsecond", _microsecond, new_longlong()),
    ("coercibility", lambda *a: 2, new_longlong()),
    ("collation", lambda v: "binary" if isinstance(v, (bytes, int, float)) else "utf8mb4_bin", new_varchar(64)),
    ("format_bytes", lambda v: None if v is None else f"{_as_num(v)} bytes", new_varchar()),
    ("any_value", lambda v: v, new_varchar()),
]


def register_all():
    for name, fn, ft in _DEFS:
        if name not in EXTENSIONS.functions:
            EXTENSIONS.register_function(name, fn, ft)


register_all()
