"""The `m`-prefix schema keyspace — catalog persistence in the store
(ref: pkg/meta/meta.go: TiDB keeps every TableInfo under the `m` prefix in
TiKV and the domain reloads the infoschema from it, domain.go:1131; a
restarted process therefore recovers its whole catalog from bytes).

Layout (all values JSON, written at a fresh TSO like meta txns):

  m\\x00t\\x00{table_id:8 big-endian}   one table's TableInfo record
  m\\x00schema                          {"version", "next_id"}

`m` sorts before the `t`-prefixed row/index keyspace, so meta never
collides with data and BR's full-range scans keep working per-table.
"""

from __future__ import annotations

import json
import struct

from .catalog import Catalog, ColumnMeta, IndexMeta, TableMeta
from ..types import Collation, Datum, DatumKind, FieldType, Flag, MyDecimal, MyTime, TypeCode

M_TABLE_PREFIX = b"m\x00t\x00"
M_TABLE_END = b"m\x00t\x01"
M_STATE_KEY = b"m\x00schema"


# ---------------------------------------------------------------- dicts
def ft_to_dict(ft: FieldType) -> dict:
    return {"tp": int(ft.tp), "flag": int(ft.flag), "flen": ft.flen,
            "decimal": ft.decimal, "charset": ft.charset, "collate": int(ft.collate),
            "elems": list(ft.elems)}


def ft_from_dict(d: dict) -> FieldType:
    return FieldType(TypeCode(d["tp"]), Flag(d["flag"]), d["flen"], d["decimal"],
                     d.get("charset", "utf8mb4"), Collation(d.get("collate", 0)),
                     tuple(d.get("elems", ())))


def datum_to_dict(d) -> dict | None:
    if d is None:
        return None
    if d.is_null():
        return {"k": "null"}
    if d.kind == DatumKind.MysqlDecimal:
        return {"k": "dec", "v": str(d.val)}
    if d.kind == DatumKind.MysqlTime:
        return {"k": "time", "v": d.val.packed}
    if d.kind in (DatumKind.String, DatumKind.Bytes):
        v = d.val if isinstance(d.val, str) else bytes(d.val).decode("utf-8", "surrogateescape")
        return {"k": "str", "v": v}
    if d.kind in (DatumKind.Float32, DatumKind.Float64):
        return {"k": "f64", "v": float(d.val)}
    if d.kind == DatumKind.Uint64:
        return {"k": "u64", "v": int(d.val)}
    return {"k": "i64", "v": int(d.val)}


def datum_from_dict(d: dict | None):
    if d is None:
        return None
    k = d["k"]
    if k == "null":
        return Datum.NULL
    if k == "dec":
        return Datum.dec(MyDecimal(d["v"]))
    if k == "time":
        return Datum.time(MyTime(d["v"]))
    if k == "str":
        return Datum.string(d["v"])
    if k == "f64":
        return Datum.f64(d["v"])
    if k == "u64":
        return Datum.u64(d["v"])
    return Datum.i64(d["v"])


def _default_to_dict(d) -> dict | None:
    """Column DEFAULT serialization: literal datums and the dynamic now()
    form cover every default the session evaluates (_eval_const handles
    Literal | FuncCall('now') | Datum)."""
    from ..parser import ast as A

    if d is None:
        return None
    if isinstance(d, Datum):
        return {"k": "datum", "v": datum_to_dict(d)}
    if isinstance(d, A.FuncCall) and d.name == "now":
        return {"k": "now"}
    if isinstance(d, A.Literal):
        return {"k": "lit", "v": d.value if not isinstance(d.value, bytes) else d.value.decode("utf-8", "surrogateescape"), "t": d.kind}
    if isinstance(d, A.UnaryOp) and d.op == "unaryminus" and isinstance(d.operand, A.Literal):
        return {"k": "neg", "v": d.operand.value, "t": d.operand.kind}
    return {"k": "repr", "v": repr(d)}  # unknown: survives as unusable marker


def _default_from_dict(d: dict | None):
    from ..parser import ast as A

    if d is None:
        return None
    if d["k"] == "datum":
        return datum_from_dict(d["v"])
    if d["k"] == "now":
        return A.FuncCall("now", [])
    if d["k"] == "lit":
        return A.Literal(d["v"], d["t"])
    if d["k"] == "neg":
        return A.UnaryOp("unaryminus", A.Literal(d["v"], d["t"]))
    return None


def table_to_dict(m: TableMeta) -> dict:
    return {
        "name": m.name,
        "table_id": m.table_id,
        "handle_col": m.handle_col,
        "row_count": m.row_count,
        "next_handle": m.peek_handle(),
        "next_col_id": m.next_col_id,
        "columns": [
            {"name": c.name, "col_id": c.col_id, "ft": ft_to_dict(c.ft),
             "origin_default": datum_to_dict(c.origin_default),
             "default": _default_to_dict(c.default),
             "auto_increment": c.auto_increment}
            for c in m.columns
        ],
        "indices": [
            {"name": i.name, "index_id": i.index_id, "col_names": i.col_names,
             "unique": i.unique, "state": i.state}
            for i in m.indices
        ],
        "partition": None if m.partition is None else {
            "method": m.partition.method,
            "col": m.partition.col,
            "parts": [{"name": p.name, "pid": p.pid, "upper": p.upper}
                      for p in m.partition.parts],
        },
    }


def table_from_dict(t: dict) -> TableMeta:
    cols = [
        ColumnMeta(
            c["name"], c["col_id"], ft_from_dict(c["ft"]),
            default=_default_from_dict(c.get("default")),
            auto_increment=c.get("auto_increment", False),
            origin_default=datum_from_dict(c.get("origin_default")),
        )
        for c in t["columns"]
    ]
    idxs = [IndexMeta(i["name"], i["index_id"], list(i["col_names"]), i["unique"],
                      i.get("state", "public")) for i in t["indices"]]
    meta = TableMeta(t["name"], t["table_id"], cols, idxs, t["handle_col"])
    pd = t.get("partition")
    if pd is not None:
        from .catalog import PartitionDef, PartitionInfo

        meta.partition = PartitionInfo(
            pd["method"], pd["col"],
            [PartitionDef(p["name"], p["pid"], p["upper"]) for p in pd["parts"]],
        )
    meta.row_count = t["row_count"]
    meta._next_handle = t["next_handle"]
    if t.get("next_col_id"):
        meta.next_col_id = t["next_col_id"]
    return meta


# ---------------------------------------------------------------- kv io
def _table_key(table_id: int) -> bytes:
    return M_TABLE_PREFIX + struct.pack(">q", table_id)


def persist_catalog(store, catalog: Catalog) -> None:
    """Write the whole catalog into the m keyspace (called after every
    schema-changing statement — the one-process analog of the reference's
    meta txn inside each DDL job)."""
    ts = store.next_ts()
    live = set()
    with catalog._lock:
        names = list(catalog._tables)
    for name in names:
        m = catalog.table(name)
        store.kv.put(_table_key(m.table_id), json.dumps(table_to_dict(m)).encode(), ts)
        live.add(m.table_id)
    # tombstone records of dropped tables
    for k, _ in store.kv.scan(M_TABLE_PREFIX, M_TABLE_END, ts):
        tid = struct.unpack(">q", k[len(M_TABLE_PREFIX):])[0]
        if tid not in live:
            store.kv.put(k, None, ts)
    with catalog._lock:
        next_id = catalog._next_id
        views_snapshot = list(catalog.views.values())
    state = {
        "version": catalog.version,
        "next_id": next_id,
        "databases": sorted(catalog.databases),
        "views": {
            v.name: {"columns": v.columns, "select": v.select_sql}
            for v in views_snapshot
        },
    }
    store.kv.put(M_STATE_KEY, json.dumps(state).encode(), ts)


def _max_row_handle(store, table_id: int) -> int | None:
    """Greatest existing row handle of a table (None when empty): the meta
    record's next_handle snapshot is only as fresh as the last DDL, while
    DML keeps allocating — the reopened allocator must rebase above the
    real keyspace (ref: meta/autoid rebase on bootstrap)."""
    import bisect

    from ..codec import tablecodec

    start = tablecodec.encode_row_key(table_id, -(1 << 63))
    end = tablecodec.encode_row_key(table_id, (1 << 63) - 1) + b"\x00"
    kv = store.kv
    with kv.lock:
        kv._ensure_sorted()
        i = bisect.bisect_left(kv._keys, end) - 1
        if i < 0:
            return None
        k = kv._keys[i]
        if not (start <= k < end):
            return None
        return tablecodec.decode_row_key(k)[1]


def load_catalog(store) -> Catalog | None:
    """Recover a Catalog from the m keyspace; None when the store carries
    no schema (fresh store). The restart analog of the domain's infoschema
    reload (ref: pkg/domain/domain.go:1131)."""
    ts = store.next_ts()
    raw = store.kv.get(M_STATE_KEY, ts)
    if raw is None:
        return None
    state = json.loads(raw)
    cat = Catalog()
    for _, v in store.kv.scan(M_TABLE_PREFIX, M_TABLE_END, ts):
        meta = table_from_dict(json.loads(v))
        for pid in meta.physical_ids():
            mh = _max_row_handle(store, pid)
            if mh is not None:
                meta.observe_handle(mh)
        with cat._lock:
            cat._tables[meta.name] = meta
    with cat._lock:
        cat._next_id = max(state["next_id"], cat._next_id)
    cat.version = state["version"]
    from .catalog import ViewMeta

    for vn, vd in state.get("views", {}).items():
        with cat._lock:
            cat.views[vn] = ViewMeta(vn, vd["columns"], vd["select"])
    cat.databases |= set(state.get("databases", []))
    return cat
