"""DDL job framework + ALTER TABLE execution (ref: pkg/ddl — the F1-style
online schema change. The reference queues jobs in system tables, an owner
schedules them, and each state transition bumps the schema version while
the domain reload loop syncs every node; in one process the executor is
synchronous, but jobs still step through the recorded states so EVERY
schema change is auditable via ADMIN SHOW DDL JOBS, and index builds pass
through delete-only -> write-only -> write-reorg -> public exactly like
pkg/ddl/index.go).

ALTER TABLE actions (ref: ddl_api.go):
  ADD COLUMN      metadata + origin default (old rows fill it at read
                  time — no table rewrite, the reference's fast path)
  DROP COLUMN     metadata removal (stored bytes become unreachable;
                  indexes on the column must be dropped first)
  MODIFY/CHANGE   same-class type changes only (widening); re-typing that
                  would reinterpret stored bytes is rejected loudly
  RENAME COLUMN / RENAME TABLE / ADD INDEX / DROP INDEX
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..parser import ast as A
from ..types import Datum
from .catalog import Catalog, CatalogError, ColumnMeta, field_type_from_spec

class DDLError(ValueError):
    pass


@dataclass
class DDLJob:
    """(ref: pkg/meta/model Job)."""

    job_id: int
    job_type: str
    table: str
    query: str
    state: str = "queueing"  # queueing -> running -> (synced | cancelled)
    schema_state: str = "none"
    start_time: float = 0.0
    end_time: float = 0.0
    error: str = ""
    states_seen: list = field(default_factory=list)


class DDLJobLog:
    """Job history (ref: the ddl job + history system tables)."""

    def __init__(self):
        self.jobs: list[DDLJob] = []  # guarded_by: _lock
        self._next = 1  # guarded_by: _lock
        self._lock = threading.Lock()

    def begin(self, job_type: str, table: str, query: str) -> DDLJob:
        with self._lock:
            job = DDLJob(self._next, job_type, table, query, start_time=time.time())
            self._next += 1
            self.jobs.append(job)
        job.state = "running"
        return job

    def step(self, job: DDLJob, schema_state: str):
        job.schema_state = schema_state
        job.states_seen.append(schema_state)

    def view(self) -> list:
        """Locked snapshot for readers on other threads (HTTP /ddl/history,
        ADMIN SHOW DDL JOBS) — `jobs` itself is guarded."""
        with self._lock:
            return list(self.jobs)

    def finish(self, job: DDLJob, error: str = ""):
        job.state = "cancelled" if error else "synced"
        job.error = error
        job.end_time = time.time()


def run_job(catalog: Catalog, job_type: str, table: str, query: str, fn, index_states: bool = False):
    """Execute one schema change as a recorded job. Index builds receive a
    `step` callback and drive the four online states THEMSELVES (the
    IndexMeta.state walk in session._build_index — each transition is a
    real visibility change for concurrent DML, and each records here as a
    schema-state step, ref: pkg/ddl job.SchemaState)."""
    log = catalog.ddl_jobs
    job = log.begin(job_type, table, query)
    try:
        if index_states:
            result = fn(lambda st: log.step(job, st))
        else:
            result = fn()
        log.step(job, "public")
        log.finish(job)
        return result
    except Exception as exc:
        log.finish(job, error=str(exc))
        raise


# ---------------------------------------------------------------- ALTER

def alter_table(session, stmt: A.AlterTableStmt):
    """Apply every spec of an ALTER TABLE, one DDL job per spec."""
    meta = session.catalog.table(stmt.table.name)
    for spec in stmt.specs:
        action = spec.action
        query = f"ALTER TABLE {meta.name} {action}"
        if action == "add_column":
            run_job(session.catalog, "add column", meta.name, query,
                    lambda s=spec, q=query: _add_column(session, meta, s, q))
        elif action == "drop_column":
            run_job(session.catalog, "drop column", meta.name, query,
                    lambda s=spec, q=query: _drop_column(session, meta, s.name, q))
        elif action in ("modify_column", "change_column"):
            run_job(session.catalog, action.replace("_", " "), meta.name, query,
                    lambda s=spec, q=query: _modify_column(session, meta, s, q))
        elif action == "rename_column":
            run_job(session.catalog, "rename column", meta.name, query,
                    lambda s=spec, q=query: _rename_column(
                        session, meta, s.name, s.new_name, q))
        elif action == "add_index":
            idx = spec.index
            if getattr(idx, "primary", False):
                raise DDLError("ADD PRIMARY KEY is not supported (handle fixed at CREATE)")
            cols = [c[0] if isinstance(c, tuple) else str(c) for c in idx.columns]
            name = idx.name or f"idx_{len(meta.indices)}"
            run_job(session.catalog, "add index", meta.name, query,
                    lambda step, n=name, cs=cols, u=idx.unique: session._build_index(meta, n, cs, u, step=step),
                    index_states=True)
        elif action == "drop_index":
            run_job(session.catalog, "drop index", meta.name, query,
                    lambda s=spec: session._drop_index_impl(meta, s.name))
        elif action == "rename":
            run_job(session.catalog, "rename table", meta.name, query,
                    lambda s=spec: _rename_table(session.catalog, meta, s.new_name or s.name))
        elif action == "set_columnar_replica":
            # ALTER TABLE t SET COLUMNAR REPLICA n (ref: TiDB's SET
            # TIFLASH REPLICA DDL creating learner replicas): n >= 1
            # attaches the changefeed-fed columnar replica, 0 detaches it
            run_job(session.catalog, "set columnar replica", meta.name, query,
                    lambda s=spec: _set_columnar_replica(session, meta, s.options.get("count", 1)))
        else:
            raise DDLError(f"ALTER TABLE action {action!r} not supported yet")


def _set_columnar_replica(session, meta, count: int):
    from ..cdc import ChangefeedError

    try:
        if count > 0:
            session.store.columnar.enable_table(session.catalog, meta)
        else:
            session.store.columnar.disable_table(meta)
    except ChangefeedError as exc:
        raise DDLError(str(exc)) from exc


def _propose_schema(session, meta, op: str, query: str) -> None:
    """A row-shape DDL just committed: ride a schema-change entry
    through the replication log so every live changefeed sees the ALTER
    as an ORDERED event between the rows committed before and after it
    (ISSUE 20 — the pre-20 behavior let feeds discover the drift and
    park). Mirror/bare stores without the propose hook have no feeds to
    inform."""
    propose = getattr(session.store, "propose_schema_change", None)
    if propose is not None:
        propose(meta, op, query)


def _add_column(session, meta, spec: A.AlterTableSpec, query: str = ""):
    cd = spec.column
    name = cd.name.lower()
    if any(c.name == name for c in meta.columns):
        raise DDLError(f"column {name!r} already exists")
    ft = field_type_from_spec(cd.type, cd.not_null)
    origin = None
    if cd.default is not None:
        origin = session._eval_const(cd.default, ft)
    elif cd.not_null:
        # MySQL implicit default for NOT NULL without DEFAULT
        from .planner import _coerce_datum

        zero = Datum.string("") if ft.is_string() else Datum.i64(0)
        origin = _coerce_datum(zero, ft) if not ft.is_string() else zero
    pos = len(meta.columns)
    if spec.position == "first":
        pos = 0
    elif spec.position.startswith("after:"):
        target = spec.position[6:].lower()
        names = [c.name for c in meta.columns]
        if target not in names:
            raise DDLError(f"unknown column {target!r} in AFTER")
        pos = names.index(target) + 1
    new_id = meta.alloc_col_id()
    from .catalog import decl_text

    cm = ColumnMeta(name, new_id, ft, cd.default, cd.auto_increment, origin_default=origin,
                    generated=cd.generated,
                    generated_stored=getattr(cd, "generated_stored", False),
                    decl=decl_text(cd.type))
    meta.columns.insert(pos, cm)
    meta.schema_version += 1  # row-shape change: replicated through the feed
    session.catalog.version += 1
    _propose_schema(session, meta, "add column", query)


def _drop_column(session, meta, name: str, query: str = ""):
    name = name.lower()
    if meta.handle_col == name:
        raise DDLError("cannot drop the PRIMARY KEY handle column")
    if meta.partition is not None and meta.partition.col == name:
        raise DDLError(f"cannot drop partitioning column {name!r}")
    if len(meta.columns) == 1:
        raise DDLError("cannot drop the last column")
    for idx in meta.indices:
        if name in idx.col_names:
            raise DDLError(f"column {name!r} is indexed by {idx.name!r}; drop the index first")
    before = len(meta.columns)
    meta.columns = [c for c in meta.columns if c.name != name]
    if len(meta.columns) == before:
        raise DDLError(f"unknown column {name!r}")
    meta.schema_version += 1  # row-shape change: replicated through the feed
    session.catalog.version += 1
    _propose_schema(session, meta, "drop column", query)


def _modify_column(session, meta, spec: A.AlterTableSpec, query: str = ""):
    cd = spec.column
    old_name = (spec.name or cd.name).lower()
    cm = meta.col(old_name)
    new_ft = field_type_from_spec(cd.type, cd.not_null)
    old_et, new_et = cm.ft.eval_type(), new_ft.eval_type()
    if old_et != new_et:
        raise DDLError(
            f"MODIFY {old_name!r}: changing {old_et} to {new_et} would reinterpret "
            "stored bytes — not supported (export + reload instead)"
        )
    if old_et == "int" and cm.ft.is_unsigned() != new_ft.is_unsigned():
        raise DDLError(f"MODIFY {old_name!r}: signedness change not supported")
    renaming = spec.action == "change_column" and cd.name.lower() != old_name
    if renaming and any(c.name == cd.name.lower() for c in meta.columns):
        # validate BEFORE mutating anything — a failed DDL must not
        # half-apply (the rename would reject after the type change)
        raise DDLError(f"column {cd.name.lower()!r} already exists")
    cm.ft = new_ft
    if renaming:
        _rename_column(session, meta, old_name, cd.name, query)
        return
    meta.schema_version += 1  # row-shape change: replicated through the feed
    session.catalog.version += 1
    _propose_schema(session, meta, "modify column", query)


def _rename_column(session, meta, old: str, new: str, query: str = ""):
    old, new = old.lower(), new.lower()
    if any(c.name == new for c in meta.columns):
        raise DDLError(f"column {new!r} already exists")
    cm = meta.col(old)
    cm.name = new
    for idx in meta.indices:
        idx.col_names = [new if c == old else c for c in idx.col_names]
    if meta.handle_col == old:
        meta.handle_col = new
    if meta.partition is not None and meta.partition.col == old:
        meta.partition.col = new
    meta.schema_version += 1  # row-shape change: replicated through the feed
    session.catalog.version += 1
    _propose_schema(session, meta, "rename column", query)


def _rename_table(catalog: Catalog, meta, new_name: str):
    new_name = new_name.lower()
    with catalog._lock:
        if new_name in catalog._tables:
            raise DDLError(f"table {new_name!r} already exists")
        del catalog._tables[meta.name]
        meta.name = new_name
        catalog._tables[new_name] = meta
        catalog.version += 1
