"""Slot rebinding over the immutable DAG IR (plan-cache dag tier).

The executors and expression nodes are frozen dataclasses, so a re-bound
DAG is rebuilt along the changed spines only — untouched subtrees (scan
column tuples, aggregate descriptors, the build pipeline of a join) are
SHARED with the cached template, which is safe because they are
immutable and makes a hit's bind cost proportional to the number of
literal slots, not the plan size."""

from __future__ import annotations

import dataclasses

from ..expr.ir import Const, Expr
from .plancache import slot_of


def iter_exec_fields(ex):
    """Yield (expr, field_name) for every Expr reachable from an
    executor's fields — the audit's search space."""
    out = []

    def walk(v, name):
        if isinstance(v, Expr):
            out.append((v, name))
            for c in getattr(v, "children", lambda: ())():
                walk(c, name)
        elif isinstance(v, (tuple, list)):
            for x in v:
                walk(x, name)
        elif dataclasses.is_dataclass(v) and not isinstance(v, type):
            for f in dataclasses.fields(v):
                walk(getattr(v, f.name), f.name)

    for f in dataclasses.fields(ex):
        walk(getattr(ex, f.name), f.name)
    return out


def rebind_dag(dag, binder, values):
    """Rebuild `dag` with every slot-tagged value replaced: Const nodes
    re-lowered through `binder(slot)`, raw int fields (TopN/Limit counts)
    replaced with the bound value. Returns the original object when
    nothing under it changed."""

    def rb(v):
        if isinstance(v, Const):
            s = slot_of(v.datum.val)
            return binder(s) if s is not None else v
        s = slot_of(v)
        if s is not None:
            return int(values[s]) if isinstance(v, int) else str(values[s])
        if isinstance(v, tuple):
            new = tuple(rb(x) for x in v)
            return new if any(a is not b for a, b in zip(new, v)) else v
        if isinstance(v, list):
            new = [rb(x) for x in v]
            return new if any(a is not b for a, b in zip(new, v)) else v
        if dataclasses.is_dataclass(v) and not isinstance(v, type):
            changed = {}
            for f in dataclasses.fields(v):
                old = getattr(v, f.name)
                new = rb(old)
                if new is not old:
                    changed[f.name] = new
            return dataclasses.replace(v, **changed) if changed else v
        return v

    return rb(dag)
