from .catalog import Catalog, CatalogError, TableMeta, field_type_from_spec
from .planner import PlanError, PlannedQuery, plan_select
from .session import Result, Session, SQLError

__all__ = [
    "Catalog",
    "CatalogError",
    "TableMeta",
    "field_type_from_spec",
    "PlanError",
    "PlannedQuery",
    "plan_select",
    "Result",
    "Session",
    "SQLError",
]
from . import builtins_host  # noqa: E402,F401 — registers the host builtin batch
