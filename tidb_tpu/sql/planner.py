"""Planner: AST -> DAGRequest (ref: pkg/planner/optimize.go:135 Optimize ->
logical rules -> physical plan -> plan_to_pb.go lowering — collapsed here
into one direct lowering pass, because the engine's only physical form is
the fused coprocessor DAG; the reference's pushdown DECISIONS live in
distsql/root.py split_dag, its EXPRESSION serialization is the ir.Expr tree
itself).

What this pass does (reference rule analogs in parens):
  - name resolution over the FROM tables (expression/column resolution)
  - join planning: probe = largest table by row count, greedy equi-join
    chaining (JoinReOrderSolver's greedy variant); per-table conjuncts push
    into each side's pipeline (PPDSolver)
  - aggregation planning incl. implicit first_row for bare columns and
    DISTINCT -> group-by rewrite (AggregationEliminator family)
  - HAVING/ORDER BY resolution against the agg output schema with alias
    support; ORDER BY+LIMIT -> TopN (PushDownTopNOptimizer's shape)
  - select-list projection / output offsets
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..exec.dag import Aggregation, ColumnInfo, DAGRequest, IndexScan, Join, Limit, Projection, Selection, Sort, TableScan, TopN
from ..expr.agg import AGG_FUNCS, AggDesc
from ..expr.ir import Expr, col, const, func, lit
from ..parser import ast as A
from ..types import Datum, DatumKind, FieldType, Flag, MyDecimal, MyTime, TypeCode, new_datetime, new_decimal, new_double, new_longlong, new_varchar
from .catalog import Catalog, CatalogError, TableMeta, field_type_from_spec

BOOL = new_longlong()


class PlanError(ValueError):
    pass


@dataclass
class PlannedQuery:
    """A lowered SELECT: the logical DAG plus what the executor needs to
    dispatch it (probe table for region ranges, build tables to broadcast)."""

    dag: DAGRequest
    probe_table: TableMeta
    build_tables: list  # [TableMeta] in canonical scan order (after probe)
    column_names: list  # output column labels
    offset: int = 0  # LIMIT offset — applied by the session on final rows
    ranges: list | None = None  # pruned scan ranges (ranger); None = full table
    access_path: str = "table"  # table | table-range | index(<name>) | index_lookup(<name>)
    # non-covering selective index: (index_id, index key ranges) — the
    # session runs the double-read (index scan -> handles -> table read,
    # ref: pkg/executor/distsql.go IndexLookUpExecutor)
    lookup: tuple | None = None
    # index merge (union): [(index_id, index key ranges), ...] — handles
    # from every member index union before the table read (ref:
    # pkg/executor/index_merge_reader.go IndexMergeReaderExecutor)
    lookup_merge: list | None = None
    # statistics-driven few-groups hint: NDV product of the group-by
    # columns when ANALYZE stats promise a small group count — routes the
    # aggregation onto the sort-free dense kernel (ops/aggregate.py);
    # a wrong promise overflows and falls back, never corrupts
    small_groups: int | None = None
    # how the scan ranges were derived — the plan cache's re-bind RECIPE
    # (ISSUE 15): ("full",) | ("handle", col) | ("index", index_id, col) |
    # ("lookup", index_id, col) | ("partition",) | ("index_merge",).
    # On a dag-tier hit, ranger re-runs over the bound conjuncts for the
    # named column — TiDB's rebuildRange-at-EXECUTE analog.
    range_src: tuple = ("full",)


# --------------------------------------------------------------------------
# scopes
# --------------------------------------------------------------------------

@dataclass
class _TableRef:
    meta: TableMeta
    alias: str
    offset: int  # column offset of this table in the combined schema


class _Scope:
    """Combined-schema name resolution (ref: expression resolver)."""

    def __init__(self, tables: list):
        self.tables = tables  # [_TableRef]

    def resolve(self, c: A.ColumnName):
        name = c.name.lower()
        tbl = c.table.lower()
        hits = []
        for tr in self.tables:
            if tbl and tr.alias != tbl and tr.meta.name != tbl:
                continue
            for i, cm in enumerate(tr.meta.columns):
                if cm.name == name:
                    hits.append((tr.offset + i, cm.ft))
        if not hits:
            raise PlanError(f"unknown column {c}")
        if len(hits) > 1:
            raise PlanError(f"ambiguous column {c}")
        return hits[0]

    def tables_of(self, node: A.ExprNode) -> set:
        """Aliases of tables referenced under `node`; ambiguous unqualified
        columns raise (MySQL ER_NON_UNIQ_ERROR), mirroring resolve()."""
        out: set = set()

        def walk(n):
            if isinstance(n, A.ColumnName):
                name, tbl = n.name.lower(), n.table.lower()
                hits = [
                    tr.alias
                    for tr in self.tables
                    if (not tbl or tr.alias == tbl or tr.meta.name == tbl)
                    and any(cm.name == name for cm in tr.meta.columns)
                ]
                if not hits:
                    raise PlanError(f"unknown column {n}")
                if len(hits) > 1:
                    raise PlanError(f"ambiguous column {n}")
                out.add(hits[0])
                return
            for c in _ast_children(n):
                walk(c)

        walk(node)
        return out


# --------------------------------------------------------------------------
# expression lowering
# --------------------------------------------------------------------------

def _ast_children(n):
    """Child ExprNodes of an AST node (one walker for every traversal —
    covers ExprNode fields, lists, and tuple entries like Case clauses)."""
    for f_ in getattr(n, "__dataclass_fields__", {}):
        v = getattr(n, f_)
        if isinstance(v, A.ExprNode):
            yield v
        elif isinstance(v, list):
            for it in v:
                if isinstance(it, A.ExprNode):
                    yield it
                elif isinstance(it, tuple):
                    for x in it:
                        if isinstance(x, A.ExprNode):
                            yield x


_CMP_OPS = {"eq", "ne", "lt", "le", "gt", "ge", "nulleq"}
_LOGIC_OPS = {"and", "or", "xor"}
_BIT_OPS = {"bitand", "bitor", "bitxor", "shiftleft", "shiftright"}


def _dec_scale(ft: FieldType) -> int:
    return max(ft.decimal, 0)


def _unify_fts(fts: list) -> FieldType:
    """Result type of branch-valued expressions (IF/CASE/COALESCE)."""
    ets = [ft.eval_type() for ft in fts]
    if "string" in ets:
        return new_varchar(max((ft.flen if ft.flen > 0 else 255) for ft in fts))
    if "real" in ets:
        return new_double()
    if "decimal" in ets:
        s = max(_dec_scale(ft) for ft in fts)
        return new_decimal(30, s)
    if "time" in ets:
        return new_datetime()
    return new_longlong()


def _arith_ft(op: str, lft: FieldType, rft: FieldType) -> FieldType:
    le, re = lft.eval_type(), rft.eval_type()
    if op in _BIT_OPS:
        return new_longlong(unsigned=True)
    if op == "intdiv":
        return new_longlong()
    if "real" in (le, re):
        return new_double()
    if op == "div":
        # decimal division: scale + 4 (ref: types DivFracIncr)
        s = max(_dec_scale(lft), _dec_scale(rft)) + 4
        return new_decimal(30, min(s, 30))
    if "decimal" in (le, re):
        s1, s2 = _dec_scale(lft), _dec_scale(rft)
        if op == "mul":
            return new_decimal(30, min(s1 + s2, 30))
        if op == "mod":
            return new_decimal(30, max(s1, s2))
        return new_decimal(30, max(s1, s2))  # plus/minus
    unsigned = lft.is_unsigned() or rft.is_unsigned()
    return new_longlong(unsigned=unsigned and op in ("plus", "mul"))


_FUNC_FTS = {
    "abs": "same", "ceil": "int_of", "ceiling": "int_of", "floor": "int_of",
    "sqrt": "real", "exp": "real", "ln": "real", "log": "real", "pow": "real",
    "power": "real", "sign": "int", "length": "int", "strcmp": "int",
    "year": "int", "month": "int", "day": "int", "dayofmonth": "int",
    "hour": "int", "minute": "int", "second": "int", "weekday": "int",
    "to_days": "int",
}

_FUNC_RENAME = {"ceiling": "ceil", "power": "pow", "dayofmonth": "day", "substring": "substr", "log": "ln"}


def _expand_row_cmp(n: A.BinaryOp) -> A.ExprNode:
    """Row-value comparison -> component expansion with SQL's own
    three-valued AND/OR semantics (ref: expression_rewriter.go
    constructBinaryOpFunction row decomposition):
      (a,b) =  (c,d)  ->  a=c AND b=d
      (a,b) <> (c,d)  ->  a<>c OR b<>d
      (a,b) <  (c,d)  ->  a<c OR (a=c AND b<d)     (lexicographic)
    """
    lt = n.left.items if isinstance(n.left, A.RowExpr) else [n.left]
    rt = n.right.items if isinstance(n.right, A.RowExpr) else [n.right]
    if len(lt) != len(rt):
        raise PlanError(f"Operand should contain {len(lt)} column(s)")
    import copy as _c

    def conj(op):
        out = None
        for a, b in zip(lt, rt):
            e = A.BinaryOp(op, _c.deepcopy(a), _c.deepcopy(b))
            out = e if out is None else A.BinaryOp("and", out, e)
        return out

    if n.op in ("eq", "nulleq"):
        return conj(n.op)
    if n.op == "ne":
        out = None
        for a, b in zip(lt, rt):
            e = A.BinaryOp("ne", _c.deepcopy(a), _c.deepcopy(b))
            out = e if out is None else A.BinaryOp("or", out, e)
        return out
    if n.op in ("lt", "le", "gt", "ge"):
        strict = {"lt": "lt", "le": "lt", "gt": "gt", "ge": "gt"}[n.op]
        out = None
        for i in range(len(lt)):
            last = i == len(lt) - 1
            op_i = n.op if last else strict
            e = A.BinaryOp(op_i, _c.deepcopy(lt[i]), _c.deepcopy(rt[i]))
            for j in range(i):
                e = A.BinaryOp("and", A.BinaryOp("eq", _c.deepcopy(lt[j]), _c.deepcopy(rt[j])), e)
            out = e if out is None else A.BinaryOp("or", out, e)
        return out
    raise PlanError(f"row-value comparison {n.op!r} not supported")


class _Lowerer:
    """AST expression -> ir.Expr against a base scope, optionally through an
    aggregation output schema (agg scope)."""

    def __init__(self, scope: _Scope, aliases: dict | None = None):
        self.scope = scope
        self.aliases = aliases or {}
        # agg context (installed by the SELECT planner when aggregating)
        self.group_asts: list = []
        self.agg_descs: list = []  # [AggDesc] in output order
        self.agg_asts: list = []  # matching AST nodes
        self.n_agg_cols = 0
        self.in_agg_ctx = False
        # window slots: id(A.WindowFunc node) -> ColumnRef into the Window
        # executor's appended output columns (installed by plan_select)
        self.window_slots: dict = {}

    def _expand_alias(self, name: str) -> Expr:
        """Lower an alias's defining expression with the alias itself masked
        out (SELECT salary*2 AS salary must not recurse forever)."""
        target = self.aliases.pop(name)
        try:
            return self.lower(target)
        finally:
            self.aliases[name] = target

    # -- agg scope helpers --------------------------------------------------
    def _group_index(self, node) -> int | None:
        for i, g in enumerate(self.group_asts):
            if g == node:
                return i
        return None

    def _agg_ref(self, desc: AggDesc, ast_node) -> Expr:
        for i, (d, a) in enumerate(zip(self.agg_descs, self.agg_asts)):
            if a == ast_node:
                return col(i, d.ft)
        self.agg_descs.append(desc)
        self.agg_asts.append(ast_node)
        return col(len(self.agg_descs) - 1, desc.ft)

    def lower_agg_func(self, n: A.AggFunc) -> Expr:
        name = n.name
        if name in ("std", "stddev", "stddev_pop"):
            name = "stddev_pop"
        if name in ("variance", "var_pop"):
            name = "var_pop"
        if name not in AGG_FUNCS:
            raise PlanError(f"aggregate {n.name!r} not supported yet")
        if name == "count" and len(n.args) == 1 and isinstance(n.args[0], A.Star):
            args = ()
        else:
            args = tuple(self.lower_base(a) for a in n.args)
        extra = None
        if name == "group_concat":
            if n.order_by:
                raise PlanError("GROUP_CONCAT(... ORDER BY) not supported yet")
            extra = n.separator if n.separator is not None else ","
        desc = AggDesc(name, args, distinct=n.distinct, extra=extra)
        return self._agg_ref(desc, n)

    # -- entry points ---------------------------------------------------------
    def lower(self, n: A.ExprNode) -> Expr:
        """Lower in the current context (agg-aware when in_agg_ctx)."""
        if self.in_agg_ctx:
            return self.lower_in_agg(n)
        return self.lower_base(n)

    def lower_in_agg(self, n: A.ExprNode) -> Expr:
        """Lower against the aggregation OUTPUT schema: agg funcs and
        group-by expressions become column refs; bare columns outside both
        get an implicit first_row (MySQL loose group-by)."""
        gi = self._group_index(n)
        if gi is not None:
            # group key columns sit after the agg columns
            g_expr = self.lower_base(self.group_asts[gi])
            return _DeferredGroupRef(gi, g_expr.ft)
        if isinstance(n, A.AggFunc):
            return self.lower_agg_func(n)
        if isinstance(n, A.ColumnName):
            if not n.table and n.name.lower() in self.aliases:
                return self._expand_alias(n.name.lower())
            fr = AggDesc("first_row", (self.lower_base(n),))
            return self._agg_ref(fr, n)
        if isinstance(n, A.Literal):
            return self.lower_base(n)
        # recurse structurally: rebuild the node with lowered children
        return self._structural(n, self.lower_in_agg)

    def _structural(self, n, rec):
        """Lower a compound node by dispatching on type with `rec` for
        children (shared between base and agg contexts)."""
        if isinstance(n, A.WindowFunc):
            slot = self.window_slots.get(id(n))
            if slot is None:
                raise PlanError(
                    f"window function {n.name!r} is only supported in the select "
                    "list and ORDER BY"
                )
            return slot
        if isinstance(n, A.BinaryOp):
            if isinstance(n.left, A.RowExpr) or isinstance(n.right, A.RowExpr):
                return rec(_expand_row_cmp(n))
            l, r = rec(n.left), rec(n.right)
            return self._binary(n.op, l, r)
        if isinstance(n, A.UnaryOp):
            a = rec(n.operand)
            if n.op == "not":
                return func("not", BOOL, a)
            if n.op == "unaryminus":
                ft = a.ft if a.ft.eval_type() in ("decimal",) else (new_double() if a.ft.eval_type() == "real" else new_longlong())
                return func("unaryminus", ft, a)
            if n.op == "bitneg":
                return func("bitneg", new_longlong(unsigned=True), a)
            raise PlanError(f"unary op {n.op}")
        if isinstance(n, A.IsNull):
            e = func("isnull", BOOL, rec(n.expr))
            return func("not", BOOL, e) if n.negated else e
        if isinstance(n, A.Between):
            x = rec(n.expr)
            lo, hi = self._coerce_const(x, rec(n.low), "lt"), self._coerce_const(x, rec(n.high), "lt")
            e = func("between", BOOL, x, lo, hi)
            return func("not", BOOL, e) if n.negated else e
        if isinstance(n, A.InList):
            if isinstance(n.expr, A.RowExpr) or any(
                isinstance(i, A.RowExpr) for i in n.items
            ):
                # (a,b) IN ((1,2),(3,4)) -> OR of row equalities, each a
                # component conjunction — SQL three-valued logic keeps the
                # NULL semantics exact (ref: expression_rewriter.go
                # buildRowExpr / the NAAJ decomposition)
                disj = None
                for i in n.items:
                    e = _expand_row_cmp(A.BinaryOp("eq", n.expr, i))
                    disj = e if disj is None else A.BinaryOp("or", disj, e)
                if n.negated:
                    disj = A.UnaryOp("not", disj)
                return rec(disj)
            x = rec(n.expr)
            items = [self._coerce_const(x, rec(i), "in") for i in n.items]
            e = func("in", BOOL, x, *items)
            return func("not", BOOL, e) if n.negated else e
        if isinstance(n, A.Like):
            e = func("like", BOOL, rec(n.expr), rec(n.pattern))
            return func("not", BOOL, e) if n.negated else e
        if isinstance(n, A.Case):
            whens = n.when_clauses
            args = []
            for cond, res in whens:
                c = self._binary("eq", rec(n.operand), rec(cond)) if n.operand is not None else rec(cond)
                args.append((c, rec(res)))
            else_e = rec(n.else_clause) if n.else_clause is not None else None
            branch_fts = [r.ft for _, r in args] + ([else_e.ft] if else_e is not None else [])
            ft = _unify_fts(branch_fts)
            flat = []
            for c, r in args:
                flat.extend((c, r))
            if else_e is not None:
                flat.append(else_e)
            return func("case", ft, *flat)
        if isinstance(n, A.Cast):
            ft = field_type_from_spec(n.to_type)
            if getattr(n.to_type, "name", "") == "date":
                # field_type_from_spec folds DATE into DATETIME storage;
                # the CAST result type keeps the DATE kind so the oracle
                # truncates the time part (ref: builtin_cast.go
                # castStringAsTime with tp mysql.TypeDate)
                ft = ft.clone()
                ft.tp = TypeCode.Date
            if n.to_type.name == "signed":
                ft = new_longlong()
            elif n.to_type.name == "unsigned":
                ft = new_longlong(unsigned=True)
            return func("cast", ft, rec(n.expr))
        if isinstance(n, A.FuncCall):
            return self._func_call(n, rec)
        if isinstance(n, A.CollateExpr):
            # expr COLLATE c: same value, comparisons use the named
            # collation (ref: expression.BuildCollationFunction) — only the
            # ci-ness matters to this engine's compare kernels
            e = rec(n.expr)
            ft = e.ft.clone()
            from ..types import Collation

            ft.collate = (
                Collation.Utf8MB4GeneralCI
                if n.collation.endswith(("_general_ci", "_0900_ai_ci", "_ci"))
                else Collation.Utf8MB4Bin
            )
            import dataclasses

            return dataclasses.replace(e, ft=ft)
        if isinstance(n, A.Regexp):
            l, r = rec(n.expr), rec(n.pattern)
            out = func("regexp", BOOL, l, r)
            return func("not", BOOL, out) if n.negated else out
        raise PlanError(f"unsupported expression {type(n).__name__}")

    _JSON_FUNCS = {
        "json_extract": "json", "json_unquote": "varchar", "json_type": "varchar",
        "json_valid": "bool", "json_length": "int", "json_keys": "json",
        "json_contains": "bool", "json_member_of": "bool", "json_array": "json",
        "json_object": "json", "json_quote": "varchar",
    }

    def _func_call(self, n: A.FuncCall, rec):
        name = _FUNC_RENAME.get(n.name, n.name)
        if name in self._JSON_FUNCS:
            from ..types import new_json

            args = [rec(a) for a in n.args]
            kind = self._JSON_FUNCS[name]
            ft = (
                new_json() if kind == "json"
                else new_varchar() if kind == "varchar"
                else new_longlong() if kind == "int"
                else BOOL
            )
            return func(name, ft, *args)
        if name in ("regexp_like",):
            return func("regexp_like", BOOL, *[rec(a) for a in n.args])
        if name in ("now", "current_timestamp", "sysdate", "current_date", "curdate", "localtime", "localtimestamp"):
            # statement-time constant (MySQL: now() is fixed per statement;
            # ref: builtin_time.go evalNowWithFsp) — volatile on host, a
            # Const by the time anything reaches the device
            import datetime as _dt

            from ..expr.ir import Const

            t = _dt.datetime.now()
            if name in ("current_date", "curdate"):
                mt = MyTime.from_ymd(t.year, t.month, t.day)
            else:
                mt = MyTime.from_ymd(t.year, t.month, t.day, t.hour, t.minute, t.second)
            return Const(Datum.time(mt), new_datetime())
        if name in ("date_add", "date_sub", "adddate", "subdate"):
            name = "date_add" if name in ("date_add", "adddate") else "date_sub"
            d = rec(n.args[0])
            iv = n.args[1]
            if not isinstance(iv, A.Interval):
                raise PlanError(f"{name} expects an INTERVAL argument")
            unit = iv.unit.lower()
            if unit not in ("second", "minute", "hour", "day", "week", "month", "quarter", "year"):
                raise PlanError(f"interval unit {unit!r} not supported")
            nexpr = rec(iv.value)
            if not d.ft.is_time():
                d = func("cast", new_datetime(), d)
            return func(name, d.ft.clone(), d, nexpr, lit(unit, new_varchar(8)))
        args = [rec(a) for a in n.args]
        if name == "extract":
            # EXTRACT(unit FROM e): simple units ride as a const string arg
            # (compile.py / eval_ref.py _op_extract dispatch); composite
            # units decompose into arithmetic over the simple ones (ref:
            # types.ExtractDatetimeNum, builtin_time.go extract)
            d = args[1]
            if not d.ft.is_time():
                d = func("cast", new_datetime(), d)
            unit = str(n.args[0].value).lower()
            LL = new_longlong()

            def part(u):
                return func(u, LL, d)

            composite = {
                "year_month": [("year", 100), ("month", 1)],
                "day_hour": [("day", 100), ("hour", 1)],
                "day_minute": [("day", 10000), ("hour", 100), ("minute", 1)],
                "day_second": [("day", 1000000), ("hour", 10000), ("minute", 100), ("second", 1)],
                "hour_minute": [("hour", 100), ("minute", 1)],
                "hour_second": [("hour", 10000), ("minute", 100), ("second", 1)],
                "minute_second": [("minute", 100), ("second", 1)],
            }
            simple = {"year", "month", "day", "hour", "minute", "second"}
            if unit not in composite and unit not in simple:
                # WEEK/QUARTER/MICROSECOND and *_MICROSECOND composites:
                # the packed kernels carry no microsecond/week machinery —
                # a clean error beats the raw unknown-scalar-op crash
                raise PlanError(f"EXTRACT unit {unit!r} not supported yet")
            if unit in composite:
                out = None
                for u, scale in composite[unit]:
                    t = part(u) if scale == 1 else func(
                        "mul", LL, part(u), lit(scale, LL)
                    )
                    out = t if out is None else func("plus", LL, out, t)
                return out
            return func("extract", new_longlong(), args[0], d)
        if name == "convert_using":
            # CONVERT(expr USING cs): value re-encoded into cs at eval time
            # (ref: pkg/expression/builtin_string.go builtinConvertSig);
            # the result type carries the target charset so downstream
            # byte-semantics functions (HEX, LENGTH, MD5...) see cs bytes
            cs = n.args[1].value if hasattr(n.args[1], "value") else "binary"
            a = args[0]
            flen = a.ft.flen if a.ft.flen and a.ft.flen > 0 else 255
            ft = new_varchar(flen)
            ft.charset = str(cs)
            if str(cs) == "binary":
                from ..types import Collation, Flag

                ft.collate = Collation.Binary
                ft.flag |= Flag.Binary
            return func("convert_using", ft, *args)
        if name == "datediff":
            a, b = args
            # string-literal dates re-parse as datetime consts (either side)
            a2 = self._coerce_const(b if b.ft.is_time() else lit("", new_datetime()), a)
            b2 = self._coerce_const(a2 if a2.ft.is_time() else lit("", new_datetime()), b)
            for x in (a2, b2):
                if not x.ft.is_time():
                    raise PlanError("datediff expects date/datetime arguments")
            return func("datediff", new_longlong(), a2, b2)
        if name in ("concat", "upper", "ucase", "lower", "lcase", "trim", "ltrim", "rtrim", "replace"):
            name = {"ucase": "upper", "lcase": "lower"}.get(name, name)
            flen = sum(max(a.ft.flen, 0) or 255 for a in args) if name == "concat" else (args[0].ft.flen if args[0].ft.flen > 0 else 255)
            return func(name, new_varchar(max(flen, 1)), *args)
        if name == "if":
            ft = _unify_fts([args[1].ft, args[2].ft])
            return func("if", ft, *args)
        if name == "ifnull":
            return func("ifnull", _unify_fts([a.ft for a in args]), *args)
        if name == "coalesce":
            return func("coalesce", _unify_fts([a.ft for a in args]), *args)
        if name == "round":
            a = args[0]
            if a.ft.eval_type() == "decimal":
                d = 0
                if len(args) > 1:
                    d = _const_int(args[1])
                return func("round", new_decimal(30, max(d, 0)), *args)
            ft = new_double() if a.ft.eval_type() == "real" else new_longlong()
            return func("round", ft, *args)
        if name == "substr":
            return func("substr", args[0].ft.clone(), *args)
        if name in _FUNC_FTS:
            kind = _FUNC_FTS[name]
            a = args[0]
            if kind == "same":
                ft = a.ft.clone()
            elif kind == "real":
                ft = new_double()
            elif kind == "int_of":
                ft = new_longlong() if a.ft.eval_type() != "real" else new_double()
            else:
                ft = new_longlong()
            return func(name, ft, *args)
        from .extension import EXTENSIONS

        cf = EXTENSIONS.functions.get(name)
        if cf is not None:
            # custom host function: lowered like a builtin, pinned to the
            # root side by the DAG splitter (extension.py module doc)
            return func(name, cf.ft, *args)
        raise PlanError(f"function {n.name!r} not supported yet")

    # -- base lowering --------------------------------------------------------
    def lower_base(self, n: A.ExprNode) -> Expr:
        if isinstance(n, A.Literal):
            return _lower_literal(n)
        if isinstance(n, A.ColumnName):
            # real columns shadow select aliases (MySQL resolution order for
            # WHERE); aliases only cover names with no underlying column
            try:
                idx, ft = self.scope.resolve(n)
                return col(idx, ft)
            except PlanError:
                if not n.table and n.name.lower() in self.aliases:
                    return self._expand_alias(n.name.lower())
                raise
        if isinstance(n, A.AggFunc):
            raise PlanError(f"aggregate {n.name} in a non-aggregated context")
        return self._structural(n, self.lower_base)

    def _binary(self, op: str, l: Expr, r: Expr) -> Expr:
        if op in _CMP_OPS:
            l, r = self._coerce_pair(l, r, op)
            return func(op, BOOL, l, r)
        if op in _LOGIC_OPS:
            return func(op, BOOL, l, r)
        ft = _arith_ft(op, l.ft, r.ft)
        return func(op, ft, l, r)

    def _coerce_pair(self, l: Expr, r: Expr, op: str = "eq"):
        return self._coerce_const(r, l, op), self._coerce_const(l, r, op)

    @staticmethod
    def _coerce_const(target: Expr, e: Expr, op: str = "eq") -> Expr:
        """String literals compared with time columns re-parse as datetime
        consts; with ENUM/SET columns they become member numbers (MySQL
        implicit coercion; ref: types/enum.go ParseEnumName)."""
        from ..expr.ir import Const

        if (
            isinstance(e, Const)
            and target.ft.is_time()
            and e.ft.is_string()
            and e.datum.val is not None
        ):
            return lit(str(e.datum.val), new_datetime())
        if (
            isinstance(e, Const)
            and target.ft.tp in (TypeCode.Enum, TypeCode.Set)
            and e.ft.is_string()
            and e.datum.val is not None
        ):
            try:
                d = _coerce_datum(e.datum, target.ft)
            except PlanError:
                # non-member literal: the -1 sentinel is match-nothing only
                # under (in)equality (member numbers are >= 1, so eq/in
                # never match and ne matches every non-NULL row); ordering
                # against it would invert range predicates, so raise there
                if op in ("eq", "ne", "nulleq", "in"):
                    return Const(Datum.i64(-1), new_longlong())
                raise PlanError(
                    f"cannot order {target.ft.tp.name} column against "
                    f"non-member literal {e.datum.val!r}"
                ) from None
            return Const(Datum.u64(int(d.val)), new_longlong(unsigned=True))
        return e


class _DeferredGroupRef(Expr):
    """Placeholder for a group-key column whose final index depends on the
    number of agg output columns (resolved by the SELECT planner)."""

    __slots__ = ("gi", "ft")

    def __init__(self, gi: int, ft: FieldType):
        self.gi = gi
        self.ft = ft

    def fingerprint(self):
        raise AssertionError("deferred ref must be resolved before use")


def _resolve_deferred(e: Expr, n_aggs: int) -> Expr:
    if isinstance(e, _DeferredGroupRef):
        return col(n_aggs + e.gi, e.ft)
    from ..expr.ir import ScalarFunc

    if isinstance(e, ScalarFunc):
        return func(e.op, e.ft, *(_resolve_deferred(a, n_aggs) for a in e.args))
    return e


def _const_int(e: Expr) -> int:
    from ..expr.ir import Const

    if isinstance(e, Const) and e.datum.val is not None:
        return int(e.datum.val)
    raise PlanError("constant integer expected")


def _coerce_datum(d: Datum, ft: FieldType) -> Datum:
    """Datum -> column type (insert/update path; ref: table.CastValue)."""
    if d.is_null():
        return d
    if ft.tp == TypeCode.Enum:
        if d.kind == DatumKind.MysqlEnum:
            return d
        if d.kind in (DatumKind.String, DatumKind.Bytes):
            name = d.val if isinstance(d.val, str) else bytes(d.val).decode()
            low = [e.lower() for e in ft.elems]
            if name.lower() not in low:
                raise PlanError(f"invalid enum value {name!r}")
            return Datum.enum_from(ft.elems, low.index(name.lower()) + 1)
        n = int(d.val)
        if not 0 < n <= len(ft.elems):
            raise PlanError(f"invalid enum number {n}")
        return Datum.enum_from(ft.elems, n)
    if ft.tp == TypeCode.Set:
        if d.kind == DatumKind.MysqlSet:
            return d
        if d.kind in (DatumKind.String, DatumKind.Bytes):
            raw = d.val if isinstance(d.val, str) else bytes(d.val).decode()
            low = [e.lower() for e in ft.elems]
            mask = 0
            for part in ([] if raw == "" else raw.split(",")):
                if part.lower() not in low:
                    raise PlanError(f"invalid set member {part!r}")
                mask |= 1 << low.index(part.lower())
            return Datum.set_from(ft.elems, mask)
        mask = int(d.val)
        return Datum.set_from(ft.elems, mask)
    et = ft.eval_type()
    if d.kind == DatumKind.MysqlJSON and et != "json":
        # JSON scalar -> SQL value (generated columns over JSON_EXTRACT,
        # CAST(json AS ...); ref: pkg/expression/builtin_cast.go json paths)
        from ..types import json_binary as _jb

        v = _jb.decode(bytes(d.val))
        if v is None:
            return Datum.NULL
        if isinstance(v, bool):
            d = Datum.i64(1 if v else 0)
        elif isinstance(v, (int, float)):
            d = Datum.i64(v) if isinstance(v, int) else Datum.f64(v)
        elif isinstance(v, str):
            d = Datum.string(v)
        else:
            d = Datum.string(_jb.to_text(v))
    if et == "decimal":
        if d.kind == DatumKind.MysqlDecimal:
            return Datum.dec(d.val.round(max(ft.decimal, 0)))
        return Datum.dec(MyDecimal(str(d.val)).round(max(ft.decimal, 0)))
    if et == "real":
        return Datum.f64(float(d.val.to_float() if d.kind == DatumKind.MysqlDecimal else d.val))
    if et == "int":
        if d.kind in (DatumKind.String, DatumKind.Bytes):
            from ..expr.eval_ref import str_prefix_f64

            return Datum.i64(int(round(str_prefix_f64(d.val))))
        if d.kind == DatumKind.MysqlDecimal:
            return Datum.i64(int(d.val.round(0).to_int()))
        if ft.is_unsigned():
            return Datum.u64(int(d.val))
        return Datum.i64(int(d.val))
    if et == "time":
        if d.kind == DatumKind.MysqlTime:
            return d
        return Datum.time(MyTime.parse(str(d.val), max(ft.decimal, 0)))
    if et == "string":
        if ft.tp == TypeCode.String and ft.charset == "binary" and ft.flen > 0:
            # BINARY(n) stores zero-padded to the declared width (ref:
            # pkg/table/column.go CastValue -> ProduceStrWithSpecifiedTp)
            b = d.val if isinstance(d.val, (bytes, bytearray)) else str(d.val).encode("utf-8")
            b = bytes(b)
            if len(b) > ft.flen:
                raise PlanError(f"Data too long for column (max {ft.flen})")
            return Datum.bytes_(b.ljust(ft.flen, b"\0"))
        if d.kind in (DatumKind.String, DatumKind.Bytes):
            return d
        return Datum.string(str(d.val))
    if et == "json":
        from ..types import json_binary as _jb

        if d.kind == DatumKind.MysqlJSON:
            return d
        if d.kind in (DatumKind.String, DatumKind.Bytes):
            txt = d.val if isinstance(d.val, str) else bytes(d.val).decode("utf-8", "surrogateescape")
            try:
                return Datum.json(_jb.encode(_jb.parse_text(txt)))
            except ValueError as exc:
                raise PlanError(f"invalid JSON text: {exc}") from exc
        if d.kind in (DatumKind.Int64, DatumKind.Uint64):
            return Datum.json(_jb.encode(int(d.val)))
        if d.kind in (DatumKind.Float32, DatumKind.Float64):
            return Datum.json(_jb.encode(float(d.val)))
        raise PlanError(f"cannot cast {d.kind.name} to JSON")
    return d


def datum_ft(d: Datum) -> FieldType:
    """Natural FieldType of a materialized datum (subquery results carry
    Datums back into expression trees as `kind="datum"` literals)."""
    if d.kind == DatumKind.Int64:
        return new_longlong()
    if d.kind == DatumKind.Uint64:
        return new_longlong(unsigned=True)
    if d.kind in (DatumKind.Float32, DatumKind.Float64):
        return new_double()
    if d.kind == DatumKind.MysqlDecimal:
        return new_decimal(max(len(str(d.val)), 1), d.val.scale)
    if d.kind == DatumKind.MysqlTime:
        return new_datetime()
    if d.kind in (DatumKind.String, DatumKind.Bytes):
        return new_varchar(max(len(str(d.val)), 1))
    return new_longlong()


def _lower_literal(n: A.Literal) -> Expr:
    if n.kind == "null":
        return lit(None, new_longlong())
    if n.kind == "datum":
        from ..expr.ir import Const

        d: Datum = n.value
        if d.is_null():
            return lit(None, new_longlong())
        return Const(d, datum_ft(d))
    if n.kind in ("int", "bool"):
        # keep int subclasses intact: the plan cache's slot-tagged
        # literals (plancache.SlotInt) must survive lowering so the
        # install-time audit can find every re-bindable Const
        v = n.value if (isinstance(n.value, int)
                        and not isinstance(n.value, bool)) else int(n.value)
        if -(1 << 63) <= v < (1 << 63):
            return lit(v, new_longlong())
        return lit(int(v), new_longlong(unsigned=True))
    if n.kind == "decimal":
        text = str(n.value)
        scale = len(text.split(".", 1)[1]) if "." in text else 0
        e = lit(None, new_decimal(max(len(text), 1), scale))
        from ..expr.ir import Const

        return Const(Datum.dec(MyDecimal(text)), e.ft)
    if n.kind == "float":
        return lit(float(str(n.value)), new_double())
    if n.kind == "str":
        v = n.value if isinstance(n.value, str) else str(n.value)
        return lit(v, new_varchar(max(len(v), 1)))
    if n.kind == "hex":
        # hex literals are VARBINARY values (ref: pkg/parser/ast/expressions.go
        # hexadecimal literal -> binary collation), NOT latin1 text: byte
        # semantics must survive into comparisons, CONCAT and INSERT targets
        from ..types import Collation, Flag

        ft = new_varchar(max(len(n.value), 1))
        ft.charset = "binary"
        ft.collate = Collation.Binary
        ft.flag |= Flag.Binary
        return const(Datum.bytes_(bytes(n.value)), ft)
    raise PlanError(f"literal kind {n.kind}")


# --------------------------------------------------------------------------
# FROM / join planning
# --------------------------------------------------------------------------

def _resolve_table(name: str, catalog: Catalog, mat: dict | None, db: str = "") -> TableMeta:
    """Materialized (CTE/derived) tables shadow catalog tables. A db
    qualifier resolves ONLY the db-scoped binding (information_schema
    memtables register under "information_schema.<name>", never shadowing
    same-named user tables)."""
    if db and db not in ("test",):
        if mat:
            m = mat.get(f"{db.lower()}.{name.lower()}")
            if m is not None:
                return m
        raise PlanError(f"unknown table {db}.{name}")
    if mat:
        m = mat.get(name.lower())
        if m is not None:
            return m
    return catalog.table(name)


def _flatten_from(node, catalog: Catalog, mat: dict | None = None) -> list:
    """FROM tree -> [(TableMeta, alias, kind, on_expr)] left-deep order.
    JOIN ... USING(cols) desugars to ON equality conjuncts."""
    if isinstance(node, A.TableName):
        meta = _resolve_table(node.name, catalog, mat, getattr(node, "db", ""))
        # an unaliased multi-db table is qualified by its SHORT name
        # (MySQL: the db prefix is not part of the column qualifier)
        return [(meta, (node.alias or node.name.rsplit(".", 1)[-1]).lower(), "inner", None)]
    if isinstance(node, A.Join):
        left = _flatten_from(node.left, catalog, mat)
        right = _flatten_from(node.right, catalog, mat)
        if len(right) != 1:
            raise PlanError("right-nested joins not supported")
        meta, alias, _, _ = right[0]
        kind = {"inner": "inner", "cross": "inner", "left": "left"}.get(node.kind)
        if kind is None:
            raise PlanError(f"join kind {node.kind!r} not supported")
        on = node.on
        if node.using:
            for cname in node.using:
                cn = cname.lower() if isinstance(cname, str) else cname.name.lower()
                lt = next((la for lm, la, _, _ in left if any(c.name == cn for c in lm.columns)), None)
                if lt is None:
                    raise PlanError(f"USING column {cn!r} not found on the left side")
                eq = A.BinaryOp("eq", A.ColumnName(cn, lt), A.ColumnName(cn, alias))
                on = eq if on is None else A.BinaryOp("and", on, eq)
        return left + [(meta, alias, kind, on)]
    raise PlanError(f"unsupported FROM clause {type(node).__name__}")


def _split_conjuncts(e: A.ExprNode | None) -> list:
    if e is None:
        return []
    if isinstance(e, A.BinaryOp) and e.op == "and":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _equi_sides(e: A.ExprNode):
    if isinstance(e, A.BinaryOp) and e.op == "eq":
        return e.left, e.right
    return None


def _has_agg(n) -> bool:
    if isinstance(n, A.AggFunc):
        return True
    return any(_has_agg(c) for c in _ast_children(n))


def _has_window(n) -> bool:
    if isinstance(n, A.WindowFunc):
        return True
    return any(_has_window(c) for c in _ast_children(n))


_WIN_NO_ARG = frozenset({"row_number", "rank", "dense_rank", "percent_rank", "cume_dist"})


def _plan_windows(win_nodes: list, low: "_Lowerer", executors: list) -> None:
    """Group the collected A.WindowFunc nodes by (partition, order) spec,
    append one Window executor per spec, and register column slots so the
    select-list lowering sees plain ColumnRefs (ref: buildWindowFunctions
    grouping same-spec functions into one Window operator)."""
    from ..exec.dag import Window as WindowExec
    from ..exec.dag import WinDesc, current_schema_fts
    from ..ops.window import WINDOW_FUNCS

    cursor = len(current_schema_fts(executors))
    specs: dict = {}
    order_keys: list = []
    for n in win_nodes:
        if getattr(n, "has_frame", False):
            raise PlanError(
                "explicit window frames (ROWS/RANGE) are not supported yet "
                "(default frames only)"
            )
        p_exprs = tuple(low.lower_base(e) for e in n.partition_by)
        o_items = tuple((low.lower_base(b.expr), b.desc) for b in n.order_by)
        key = tuple(p.fingerprint() for p in p_exprs) + ("|",) + tuple(
            (e.fingerprint(), d) for e, d in o_items
        )
        if key not in specs:
            specs[key] = (p_exprs, o_items, [])
            order_keys.append(key)
        specs[key][2].append(n)

    for key in order_keys:
        p_exprs, o_items, nodes = specs[key]
        descs = []
        for n in nodes:
            name = n.name.lower()
            if name not in WINDOW_FUNCS:
                raise PlanError(f"window function {name!r} not supported")
            args: tuple = ()
            offset, default = 1, None
            if name in _WIN_NO_ARG:
                if n.args:
                    raise PlanError(f"{name}() takes no arguments")
            elif name == "ntile":
                if len(n.args) != 1:
                    raise PlanError("ntile(n) takes one argument")
                offset = _const_int(low.lower_base(n.args[0]))
                if offset < 1:
                    raise PlanError("ntile argument must be >= 1")
            elif name in ("lead", "lag"):
                if not (1 <= len(n.args) <= 3):
                    raise PlanError(f"{name}(expr[, offset[, default]])")
                args = (low.lower_base(n.args[0]),)
                if len(n.args) > 1:
                    offset = _const_int(low.lower_base(n.args[1]))
                if len(n.args) > 2:
                    default = low.lower_base(n.args[2])
                    # value and default unify to one result type (MySQL
                    # unifies them; the device kernel mixes their lanes)
                    uft = _unify_fts([args[0].ft, default.ft])
                    if args[0].ft.eval_type() != uft.eval_type() or _dec_scale(args[0].ft) != _dec_scale(uft):
                        args = (func("cast", uft, args[0]),)
                    if default.ft.eval_type() != uft.eval_type() or _dec_scale(default.ft) != _dec_scale(uft):
                        default = func("cast", uft, default)
            elif name == "nth_value":
                if len(n.args) != 2:
                    raise PlanError("nth_value(expr, n) takes two arguments")
                args = (low.lower_base(n.args[0]),)
                offset = _const_int(low.lower_base(n.args[1]))
                if offset < 1:
                    raise PlanError("nth_value position must be >= 1")
            elif name == "count" and len(n.args) == 1 and isinstance(n.args[0], A.Star):
                args = ()
            else:
                if len(n.args) != 1:
                    raise PlanError(f"window {name}() takes one argument")
                args = (low.lower_base(n.args[0]),)
            descs.append(WinDesc(name, args, _win_ft(name, args), offset, default))
            low.window_slots[id(n)] = col(cursor, descs[-1].ft)
            cursor += 1
        executors.append(WindowExec(p_exprs, o_items, tuple(descs)))


def _win_ft(name: str, args: tuple) -> FieldType:
    """Window result type (ref: aggfuncs type inference per function)."""
    if name in ("row_number", "rank", "dense_rank", "ntile", "count"):
        return new_longlong(notnull=True)
    if name in ("percent_rank", "cume_dist"):
        return new_double()
    if name in ("sum", "avg"):
        return AggDesc(name, args).ft
    return args[0].ft.clone_nullable()


def _referenced_columns(stmt: A.SelectStmt, meta: TableMeta) -> set:
    """All column names a single-table SELECT touches (star = every
    column) — the covering-index eligibility set."""
    names: set = set()
    star = [False]

    def walk(n):
        if isinstance(n, A.Star):
            star[0] = True
            return
        if isinstance(n, A.ColumnName):
            names.add(n.name.lower())
            return
        if isinstance(n, A.AggFunc):
            # count(*) references no columns — its Star is not select-star
            for a in n.args:
                if not isinstance(a, A.Star):
                    walk(a)
            for b in n.order_by:
                walk(b.expr)
            return
        for c in _ast_children(n):
            walk(c)

    for f in stmt.fields:
        walk(f.expr if isinstance(f, A.SelectField) else f)
    if stmt.where is not None:
        walk(stmt.where)
    for b in stmt.group_by:
        walk(b.expr)
    if stmt.having is not None:
        walk(stmt.having)
    for b in stmt.order_by:
        walk(b.expr)
    if star[0]:
        names |= {c.name for c in meta.columns}
    return names


def _field_label(f: A.SelectField) -> str:
    """MySQL column titles: alias > column name as written (unqualified,
    quotes stripped) > the expression's verbatim source text (ref: field
    name derivation in the reference's buildProjectionField)."""
    if f.alias:
        return f.alias
    src = getattr(f, "source", "") or ""
    if isinstance(f.expr, A.ColumnName):
        if src and "(" not in src:
            if "`" in src:
                # backquoted identifiers may CONTAIN dots: take the last
                # quoted segment verbatim (`t`.`a.b` titles as a.b)
                import re as _re

                parts = _re.findall(r"`((?:[^`]|``)*)`", src)
                if parts:
                    return parts[-1].replace("``", "`")
            return src.split(".")[-1].strip().strip("`") or f.expr.name
        return f.expr.name
    if isinstance(f.expr, A.Literal) and f.expr.kind == "str" and src[:1] in ("'", '"'):
        # MySQL titles a bare string literal with its VALUE, quotes gone
        return str(f.expr.value)
    if src:
        # MySQL folds no-op unary + out of titles ('+1' -> '1',
        # '+ "x"' -> 'x') but keeps mixed-sign prefixes ('+ - 1', '+-+1')
        rest = src
        while rest[:1] == "+":
            rest = rest[1:].lstrip()
        if rest != src and rest[:1] != "-":
            if rest[:1] in ("'", '"') and len(rest) >= 2 and rest[-1] == rest[0]:
                return rest[1:-1]
            return rest
        return src
    if isinstance(f.expr, A.AggFunc):
        return f"{f.expr.name}(...)"
    return "expr"


def _build_keys_unique(meta, build_keys) -> bool:
    """True when the build-side join keys are provably unique per build row
    — the table's integer PK handle or a unique index covering exactly the
    key columns. The kernel then skips the join fan-out expansion (dag.py
    Join.build_unique; ref: hash_join_v2.go one-row-per-key row table).
    Build pipelines here are scan[+selection], so key ColumnRef indexes map
    straight onto meta.columns; filtering only removes rows, never breaks
    uniqueness. Conservative: any non-bare-column key disqualifies."""
    from ..expr.ir import ColumnRef

    names = set()
    for k in build_keys:
        if not isinstance(k, ColumnRef) or k.index >= len(meta.columns):
            return False
        names.add(meta.columns[k.index].name)
    if meta.handle_col is not None and names == {meta.handle_col}:
        return True
    return any(im.unique and set(im.col_names) == names for im in meta.indices)


def _unify_join_key(pk: Expr, bk: Expr):
    """Bring both key sides to one eval class/scale (ref: hash join key
    unification in the planner — casts inserted so the kernel's normalized
    key words agree)."""
    pe, be = pk.ft.eval_type(), bk.ft.eval_type()
    if pe == be:
        if pe == "decimal" and _dec_scale(pk.ft) != _dec_scale(bk.ft):
            s = max(_dec_scale(pk.ft), _dec_scale(bk.ft))
            tgt = new_decimal(30, s)
            return func("cast", tgt, pk), func("cast", tgt, bk)
        if pe == "int" and pk.ft.is_unsigned() != bk.ft.is_unsigned():
            tgt = new_longlong(unsigned=False)
            return func("cast", tgt, pk), func("cast", tgt, bk)
        return pk, bk
    classes = {pe, be}
    if "real" in classes:
        tgt = new_double()
    elif "decimal" in classes and classes <= {"decimal", "int"}:
        s = max(_dec_scale(pk.ft), _dec_scale(bk.ft))
        tgt = new_decimal(30, s)
    elif classes <= {"int", "time"}:
        tgt = new_longlong()
    else:
        raise PlanError(f"cannot join keys of classes {pe} and {be}")

    def cast(e):
        return e if e.ft.eval_type() == tgt.eval_type() and _dec_scale(e.ft) == _dec_scale(tgt) else func("cast", tgt, e)

    return cast(pk), cast(bk)


def range_const_of(ft: FieldType):
    """Literal -> Datum of the column's type for range building. When the
    coercion is LOSSY (1.5 rounded to 2 for an int column) the original
    bound semantics would prune matching rows — decline, the conjunct stays
    as a plain filter (ref: ranger's points conversion refuses inexact
    casts)."""
    from ..expr.eval_ref import compare

    numeric = (DatumKind.Int64, DatumKind.Uint64, DatumKind.Float32, DatumKind.Float64, DatumKind.MysqlDecimal)

    def ev(lit_ast):
        d = _lower_literal(lit_ast).datum
        cd = _coerce_datum(d, ft)
        if d.kind in numeric and cd.kind in numeric and compare(d, cd) != 0:
            return None
        return cd

    return ev


def estimate_table_rows(meta: TableMeta, conjuncts: list, catalog: Catalog) -> float:
    """Filtered-cardinality estimate for one table: ANALYZE histograms when
    available (ref: pkg/statistics Selectivity), else the raw row count.
    Per-column interval selectivities multiply (independence assumption,
    as the reference's default without column groups)."""
    from .ranger import intervals_for_column
    from .stats import est_selectivity

    tstats = catalog.stats.get(meta.table_id)
    base = float(tstats.row_count if tstats is not None else meta.row_count)
    if tstats is None or not conjuncts:
        return base
    sel = 1.0
    for cm in meta.columns:
        cs = tstats.columns.get(cm.name)
        if cs is None:
            continue
        ivs = intervals_for_column(conjuncts, cm.name, range_const_of(cm.ft))
        if ivs is None:
            continue
        if not ivs:
            return 0.0
        sel *= est_selectivity(cs, ivs)
    return base * sel


class _HintSet:
    """Parsed /*+ ... */ hints the planner consumes (ref: pkg/util/hint
    TableHintInfo): USE_INDEX / FORCE_INDEX / IGNORE_INDEX,
    HASH_JOIN_PROBE / HASH_JOIN_BUILD. Unknown hints are ignored, like the
    reference's warning-only handling."""

    def __init__(self, raw):
        self.use_index: dict = {}
        self.ignore_index: dict = {}
        self._probe: list = []
        self._build: list = []
        self.use_index_merge = False
        self.no_index_merge = False
        for name, args in raw or []:
            if name in ("use_index", "force_index") and args:
                self.use_index.setdefault(args[0].lower(), set()).update(a.lower() for a in args[1:])
            elif name == "ignore_index" and args:
                self.ignore_index.setdefault(args[0].lower(), set()).update(a.lower() for a in args[1:])
            elif name in ("hash_join_probe", "hash_join") and args:
                self._probe.append(args[0].lower())
            elif name == "hash_join_build" and args:
                self._build.append(args[0].lower())
            elif name == "use_index_merge":
                self.use_index_merge = True
            elif name == "no_index_merge":
                self.no_index_merge = True

    def index_allowed(self, alias: str, idx_name: str) -> bool:
        if idx_name.lower() in self.ignore_index.get(alias, ()):  # noqa: SIM103
            return False
        use = self.use_index.get(alias)
        if use is not None and use and idx_name.lower() not in use:
            return False
        return True

    def index_forced(self, alias: str, idx_name: str) -> bool:
        return idx_name.lower() in self.use_index.get(alias, set())

    def probe_alias(self, aliases):
        for a in self._probe:
            if a in aliases:
                return a
        return None

    def build_alias(self, aliases):
        for a in self._build:
            if a in aliases:
                return a
        return None


def _split_disjuncts(e):
    out = []

    def walk(x):
        if isinstance(x, A.BinaryOp) and x.op == "or":
            walk(x.left)
            walk(x.right)
        else:
            out.append(x)

    walk(e)
    return out


def plan_select(stmt: A.SelectStmt, catalog: Catalog, mat: dict | None = None, enable_index_merge: bool = False) -> PlannedQuery:
    """Span-instrumented entry (ref: the optimizer trace hooks in
    pkg/planner/optimize.go); _plan_select does the work."""
    from ..util import tracing

    with tracing.span("planner.plan") as sp:
        plan = _plan_select(stmt, catalog, mat, enable_index_merge)
        if sp is not None:
            sp.set("access_path", plan.access_path)
            sp.set("probe_table", plan.probe_table.name)
        return plan


def _plan_select(stmt: A.SelectStmt, catalog: Catalog, mat: dict | None = None, enable_index_merge: bool = False) -> PlannedQuery:
    if (isinstance(stmt.from_clause, A.TableName)
            and stmt.from_clause.name.lower() == "dual"
            and not getattr(stmt.from_clause, "db", "")):
        # FROM DUAL is the no-table SELECT (ref: parser.y TableRefsClause
        # DUAL production; MySQL compat)
        stmt.from_clause = None
    if stmt.from_clause is None:
        raise PlanError("SELECT without FROM is evaluated by the session")
    if stmt.ctes:
        raise PlanError("CTEs are materialized by the session before planning")
    flat = _flatten_from(stmt.from_clause, catalog, mat)
    hints = _HintSet(getattr(stmt, "hints", []))

    # ---- join order: probe = largest table (row-count stat); LEFT JOIN
    # pins the textual order (outer semantics are order-sensitive)
    textual_order = [(meta, alias) for meta, alias, _, _ in flat]  # for SELECT *
    has_left = any(kind == "left" for _, _, kind, _ in flat)
    if not has_left and len(flat) > 1:
        # probe = table with the LARGEST estimated post-filter cardinality
        # (build sides broadcast; ref: physical optimizer's row-count-driven
        # build/probe selection, exhaust_physical_plans.go)
        tmp_refs, off0 = [], 0
        for m_, a_, _, _ in flat:
            tmp_refs.append(_TableRef(m_, a_, off0))
            off0 += len(m_.columns)
        tmp_scope = _Scope(tmp_refs)
        per_alias: dict = {a_: [] for _, a_, _, _ in flat}
        for c in _split_conjuncts(stmt.where):
            if isinstance(c, A.SemiJoinCond):
                continue
            try:
                tabs = tmp_scope.tables_of(c)
            except PlanError:
                continue
            if len(tabs) == 1:
                per_alias[next(iter(tabs))].append(c)
        est = [
            estimate_table_rows(m_, per_alias[a_], catalog)
            for m_, a_, _, _ in flat
        ]
        probe_i = max(range(len(flat)), key=lambda i: est[i])
        # /*+ HASH_JOIN_PROBE(t) / HASH_JOIN_BUILD(t) */ override the
        # cardinality choice (ref: pkg/util/hint HintHJProbe/HintHJBuild
        # consumed in exhaust_physical_plans)
        aliases_flat = [a_ for _, a_, _, _ in flat]
        hp = hints.probe_alias(aliases_flat)
        if hp is not None:
            probe_i = aliases_flat.index(hp)
        else:
            hb = hints.build_alias(aliases_flat)
            if hb is not None and len(flat) > 1:
                others = [i for i in range(len(flat)) if aliases_flat[i] != hb]
                probe_i = max(others, key=lambda i: est[i])
        flat = [flat[probe_i]] + flat[:probe_i] + flat[probe_i + 1 :]

    # ---- scope over the combined schema in placement order
    trefs = []
    off = 0
    for meta, alias, _, _ in flat:
        trefs.append(_TableRef(meta, alias, off))
        off += len(meta.columns)
    scope = _Scope(trefs)
    aliases = {f.alias.lower(): f.expr for f in stmt.fields if isinstance(f, A.SelectField) and f.alias}
    low = _Lowerer(scope, aliases)

    # ---- conjunct classification (PPDSolver analog)
    where_conj = _split_conjuncts(stmt.where)
    # decorrelated-subquery markers become semi/anti join steps after the
    # regular joins (ref: rule_decorrelate.go producing semi LogicalJoins)
    semi_conds = [c for c in where_conj if isinstance(c, A.SemiJoinCond)]
    where_conj = [c for c in where_conj if not isinstance(c, A.SemiJoinCond)]
    on_conj_per_join: dict[int, list] = {}
    for i, (_, _, kind, on) in enumerate(flat):
        if on is None:
            continue
        if kind == "left":
            on_conj_per_join[i] = _split_conjuncts(on)
        else:
            where_conj.extend(_split_conjuncts(on))  # inner: ON == WHERE

    # WHERE conjuncts on a LEFT JOIN's null-supplied side must run AFTER
    # null extension (post-join residual), never inside the build pipeline
    left_build_aliases = {trefs[i].alias for i in range(1, len(trefs)) if flat[i][2] == "left"}
    local: dict[str, list] = {tr.alias: [] for tr in trefs}
    equi: list = []  # (tables frozenset, lhs_ast, rhs_ast)
    residual: list = []
    for c in where_conj:
        tabs = scope.tables_of(c)
        if len(tabs) <= 1:
            alias1 = next(iter(tabs)) if tabs else None
            if alias1 is not None and alias1 not in left_build_aliases:
                local[alias1].append(c)
            else:
                residual.append(c)  # const condition / left-side filter
            continue
        sides = _equi_sides(c)
        if sides is not None and len(tabs) == 2:
            lt, rt = scope.tables_of(sides[0]), scope.tables_of(sides[1])
            if len(lt) == 1 and len(rt) == 1 and lt != rt:
                equi.append((tabs, sides[0], sides[1]))
                continue
        residual.append(c)

    # ---- access path (ranger): covering index scan / PK handle pruning
    from .ranger import handle_ranges_from_intervals, index_ranges_from_intervals, intervals_for_column

    probe_meta, probe_alias = trefs[0].meta, trefs[0].alias
    scan_ranges = None
    access_path = "table"
    range_src = ("full",)
    probe_scan = TableScan(probe_meta.table_id, probe_meta.scan_columns())

    if len(trefs) == 1 and probe_meta.indices:
        # covering index: every referenced column lives in the index (or is
        # the handle) AND its first column is range-constrained
        # (ref: physical access-path selection, find_best_task.go)
        from .catalog import ColumnMeta

        referenced = _referenced_columns(stmt, probe_meta)
        for idx in probe_meta.indices:
            if idx.state != "public":
                continue  # building indexes are invisible to readers (F1)
            if not hints.index_allowed(probe_alias, idx.name):
                continue
            covered = set(idx.col_names) | ({probe_meta.handle_col} if probe_meta.handle_col else set())
            if not referenced <= covered:
                continue
            first = probe_meta.col(idx.col_names[0])
            ivs = intervals_for_column(local[probe_alias], first.name, range_const_of(first.ft))
            if ivs is None:
                continue
            # entry layout = [index cols..., handle]; the resolution schema
            # must align slot for slot with the IndexScan output
            vcols = [probe_meta.col(cn) for cn in idx.col_names]
            vmetas = [ColumnMeta(c.name, c.col_id, c.ft) for c in vcols]
            handle_ft = new_longlong(notnull=True)
            if probe_meta.handle_col and probe_meta.handle_col not in idx.col_names:
                vmetas.append(ColumnMeta(probe_meta.handle_col, -1, handle_ft))
            else:
                vmetas.append(ColumnMeta("_tidb_rowid", -1, handle_ft))
            virtual = TableMeta(probe_meta.name, probe_meta.table_id, vmetas, [], probe_meta.handle_col)
            icols = tuple(ColumnInfo(c.col_id, c.ft) for c in vmetas)
            probe_scan = IndexScan(probe_meta.table_id, idx.index_id, icols)
            scan_ranges = index_ranges_from_intervals(probe_meta.table_id, idx.index_id, ivs)
            access_path = f"index({idx.name})"
            range_src = ("index", idx.index_id, first.name)
            # rebind resolution to the index entry schema
            trefs = [_TableRef(virtual, probe_alias, 0)]
            scope = _Scope(trefs)
            low = _Lowerer(scope, aliases)
            break
    if access_path == "table" and probe_meta.handle_col is not None and probe_meta.partition is None:
        hcol = probe_meta.col(probe_meta.handle_col)
        ivs = intervals_for_column(local[probe_alias], hcol.name, range_const_of(hcol.ft))
        if ivs is not None:
            scan_ranges = handle_ranges_from_intervals(probe_meta.table_id, ivs)
            access_path = "table-range"
            range_src = ("handle", hcol.name)

    if probe_meta.partition is not None and access_path in ("table", "table-range"):
        # partition pruning (ref: rule_partition_processor.go): intervals
        # on the partition column choose the physical partitions to scan;
        # each pruned partition contributes its own key-space ranges (and
        # its handle ranges when the PK is the partition column)
        from ..distsql.dispatch import full_table_ranges

        pcm = probe_meta.col(probe_meta.partition.col)
        pivs = intervals_for_column(local[probe_alias], pcm.name, range_const_of(pcm.ft))
        pruned = probe_meta.partition.prune(pivs)
        if pivs is not None and probe_meta.handle_col == probe_meta.partition.col:
            scan_ranges = [
                r for p in pruned for r in handle_ranges_from_intervals(p.pid, pivs)
            ]
        else:
            scan_ranges = [r for p in pruned for r in full_table_ranges(p.pid)]
        access_path += f" partitions({','.join(p.name for p in pruned)})"
        range_src = ("partition",)

    lookup = None
    if access_path == "table" and len(trefs) == 1 and probe_meta.indices:
        # non-covering index with a range-constrained first column AND a
        # selective predicate: the index-lookup double-read reads o(table)
        # rows (ref: IndexLookUpExecutor pkg/executor/distsql.go; the
        # cost-based choice mirrors find_best_task's row-count comparison)
        from .stats import est_selectivity

        tstats = catalog.stats.get(probe_meta.table_id)
        best = None
        for idx in probe_meta.indices:
            if idx.state != "public":
                continue  # building indexes are invisible to readers (F1)
            if not hints.index_allowed(probe_alias, idx.name):
                continue
            first = probe_meta.col(idx.col_names[0])
            ivs = intervals_for_column(local[probe_alias], first.name, range_const_of(first.ft))
            if ivs is None:
                continue
            if hints.index_forced(probe_alias, idx.name):
                best = (-1.0, idx, ivs)  # forced: beats any selectivity
                break
            cs = tstats.columns.get(first.name) if tstats is not None else None
            if cs is not None:
                sel = est_selectivity(cs, ivs) if ivs else 0.0
            else:
                # no stats: assume point intervals are selective, ranges not
                from ..expr.eval_ref import compare as _cmp

                point = all(
                    iv.low is not None and iv.high is not None and _cmp(iv.low, iv.high) == 0
                    for iv in ivs
                )
                sel = 0.1 if point else 1.0
            if best is None or sel < best[0]:
                best = (sel, idx, ivs)
        # double-read pays a per-row point cost: require clear selectivity
        if best is not None and best[0] < 0.3:
            _, idx, ivs = best
            lookup = (idx.index_id, index_ranges_from_intervals(probe_meta.table_id, idx.index_id, ivs))
            access_path = f"index_lookup({idx.name})"
            range_src = ("lookup", idx.index_id, probe_meta.col(idx.col_names[0]).name)

    lookup_merge = None
    if (
        access_path == "table" and len(trefs) == 1 and probe_meta.indices
        and (enable_index_merge or hints.use_index_merge) and not hints.no_index_merge
    ):
        # index merge (UNION): one top-level OR-disjunction whose every
        # disjunct range-constrains some index's first column — handles
        # union before the table read; the retained Selection re-applies
        # the full predicate, so the union is a safe over-approximation
        # (ref: planner index-merge path generation + index_merge_reader.go)
        for c in local[probe_alias]:
            disj = _split_disjuncts(c)
            if len(disj) < 2:
                continue
            parts = []
            for d in disj:
                found = None
                for idx in probe_meta.indices:
                    if idx.state != "public":
                        continue
                    if not hints.index_allowed(probe_alias, idx.name):
                        continue
                    first = probe_meta.col(idx.col_names[0])
                    ivs = intervals_for_column([d], first.name, range_const_of(first.ft))
                    if ivs is not None:
                        found = (idx, ivs)
                        break
                if found is None:
                    parts = None
                    break
                parts.append(found)
            if parts:
                lookup_merge = [
                    (i.index_id, index_ranges_from_intervals(probe_meta.table_id, i.index_id, iv))
                    for i, iv in parts
                ]
                names_ = ",".join(i.name for i, _ in parts)
                access_path = f"index_merge(union:{names_})"
                range_src = ("index_merge",)
                break

    # ---- probe pipeline
    executors: list = [probe_scan]
    if local[probe_alias]:
        executors.append(Selection(tuple(low.lower_base(c) for c in local[probe_alias])))

    # ---- joins (left-deep, broadcast build sides)
    placed = {probe_alias}
    build_tables = []
    for i in range(1, len(trefs)):
        tr = trefs[i]
        meta, alias, kind = flat[i][0], tr.alias, flat[i][2]
        local_scope = _Scope([_TableRef(meta, alias, 0)])
        local_low = _Lowerer(local_scope)
        build_execs: list = [TableScan(meta.table_id, meta.scan_columns())]

        join_preds = []
        pool = equi
        if kind == "left":
            # ON conjuncts: build-local filters go inside the build
            # pipeline; equi preds become keys; anything else is unsupported
            pool = []
            for c in on_conj_per_join.get(i, []):
                tabs = scope.tables_of(c)
                if tabs == {alias}:
                    local[alias].append(c)
                    continue
                sides = _equi_sides(c)
                if sides is not None and len(tabs) == 2:
                    pool.append((tabs, sides[0], sides[1]))
                    continue
                raise PlanError("LEFT JOIN ON supports equi conditions and build-side filters only")
        if local[alias]:
            build_execs.append(Selection(tuple(local_low.lower_base(c) for c in local[alias])))

        probe_keys, build_keys = [], []
        remaining = []
        for tabs, l_ast, r_ast in pool:
            if alias in tabs and tabs - {alias} <= placed:
                l_tabs = scope.tables_of(l_ast)
                b_ast, p_ast = (l_ast, r_ast) if l_tabs == {alias} else (r_ast, l_ast)
                pk = low.lower_base(p_ast)
                bk = local_low.lower_base(b_ast)
                pk, bk = _unify_join_key(pk, bk)
                probe_keys.append(pk)
                build_keys.append(bk)
            else:
                remaining.append((tabs, l_ast, r_ast))
        if kind != "left":
            equi = remaining
        if not probe_keys:
            # cartesian product: constant keys (every row matches)
            probe_keys = [lit(1, new_longlong(notnull=True))]
            build_keys = [lit(1, new_longlong(notnull=True))]
        executors.append(
            Join(
                build=tuple(build_execs),
                probe_keys=tuple(probe_keys),
                build_keys=tuple(build_keys),
                join_type="left_outer" if kind == "left" else "inner",
                build_unique=_build_keys_unique(meta, build_keys),
            )
        )
        placed.add(alias)
        build_tables.append(meta)

    # ---- decorrelated semi/anti joins (schema unchanged: probe rows only)
    for sc in semi_conds:
        smeta = _resolve_table(sc.table, catalog, mat)
        s_scope = _Scope([_TableRef(smeta, smeta.name, 0)])
        s_low = _Lowerer(s_scope)
        build_execs = (TableScan(smeta.table_id, smeta.scan_columns()),)
        probe_keys, build_keys = [], []
        for pe, bc in zip(sc.probe_exprs, sc.build_cols):
            pk = low.lower_base(pe)
            if sc.anti and sc.require_notnull_probe and not (pk.ft.flag & Flag.NotNull):
                raise PlanError(
                    "NOT IN over a correlated subquery requires a NOT NULL left operand "
                    "(NULL-valued operands would change the three-valued result)"
                )
            bk = s_low.lower_base(A.ColumnName(bc))
            pk, bk = _unify_join_key(pk, bk)
            probe_keys.append(pk)
            build_keys.append(bk)
        executors.append(
            Join(
                build=build_execs,
                probe_keys=tuple(probe_keys),
                build_keys=tuple(build_keys),
                join_type="anti" if sc.anti else "semi",
            )
        )
        build_tables.append(smeta)
    if equi:
        # equi preds that never matched a join step (e.g. cycles) filter post-join
        for tabs, l_ast, r_ast in equi:
            residual.append(A.BinaryOp("eq", l_ast, r_ast))
    if residual:
        executors.append(Selection(tuple(low.lower_base(c) for c in residual)))

    # ---- select list: expand * / t.* first — in TEXTUAL FROM order (the
    # probe reorder must not change the user-visible column order)
    fields: list = []
    for f in stmt.fields:
        e = f.expr if isinstance(f, A.SelectField) else f
        if isinstance(e, A.Star):
            for meta, alias in textual_order:
                if e.table and alias != e.table.lower() and meta.name != e.table.lower():
                    continue
                for cm in meta.columns:
                    fields.append(A.SelectField(A.ColumnName(cm.name, alias), cm.name))
        else:
            fields.append(f)

    def positional(e):
        """ORDER BY 1 / GROUP BY 2 = select-list position (MySQL)."""
        if isinstance(e, A.Literal) and e.kind == "int":
            i = int(e.value)
            if not (1 <= i <= len(fields)):
                raise PlanError(f"ORDER/GROUP BY position {i} out of range")
            return fields[i - 1].expr
        return e

    # ---- window functions (ref: logical_plan_builder buildWindowFunctions;
    # exhaust_physical_plans window enforcement; plan_to_pb.go:663)
    win_nodes: list = []

    def collect_wins(x):
        if isinstance(x, A.WindowFunc):
            win_nodes.append(x)
            return
        for c in _ast_children(x):
            collect_wins(c)

    for f in fields:
        collect_wins(f.expr)
    for b in stmt.order_by:
        collect_wins(b.expr)
    if stmt.having is not None and _has_window(stmt.having):
        raise PlanError("window functions are not allowed in HAVING")
    if win_nodes:
        if stmt.group_by or any(_has_agg(f.expr) for f in fields) or (
            stmt.having is not None and _has_agg(stmt.having)
        ):
            raise PlanError("mixing window functions with GROUP BY/aggregates not supported yet")
        _plan_windows(win_nodes, low, executors)

    # ---- aggregation
    group_asts = [positional(b.expr) for b in stmt.group_by]
    need_agg = bool(group_asts) or any(_has_agg(f.expr) for f in fields) or (
        stmt.having is not None and _has_agg(stmt.having)
    )
    if stmt.distinct and not need_agg:
        # SELECT DISTINCT a, b == GROUP BY a, b (AggregationEliminator dual)
        group_asts = [f.expr for f in fields]
        need_agg = True

    names = [_field_label(f) for f in fields]

    if need_agg:
        low.group_asts = group_asts
        low.in_agg_ctx = True
        out_exprs = [low.lower_in_agg(f.expr) for f in fields]
        having_e = low.lower_in_agg(stmt.having) if stmt.having is not None else None
        order_items = [(low.lower_in_agg(positional(b.expr)), b.desc) for b in stmt.order_by]
        n_aggs = len(low.agg_descs)
        out_exprs = [_resolve_deferred(e, n_aggs) for e in out_exprs]
        having_e = _resolve_deferred(having_e, n_aggs) if having_e is not None else None
        order_items = [(_resolve_deferred(e, n_aggs), d) for e, d in order_items]
        groups = tuple(low.lower_base(g) for g in group_asts)
        # StreamAgg: a covering IndexScan yields rows in index-key order,
        # so a GROUP BY on a prefix of the index columns (bare ColumnRefs,
        # in order) is already sorted — the boundary-scan kernel applies
        # (ref: agg_stream_executor.go; physical prop enforcement in
        # find_best_task choosing StreamAgg over sorted sources)
        stream = False
        from ..expr.ir import ColumnRef as _CRef

        if (
            isinstance(probe_scan, IndexScan)
            and groups
            and not any(d.distinct for d in low.agg_descs)
            and all(isinstance(g, _CRef) for g in groups)
            and [g.index for g in groups] == list(range(len(groups)))
        ):
            stream = True
        executors.append(Aggregation(group_by=groups, aggs=tuple(low.agg_descs), stream=stream))
        if having_e is not None:
            executors.append(Selection((having_e,)))
    else:
        out_exprs = [low.lower_base(f.expr) for f in fields]
        order_items = [(low.lower_base(positional(b.expr)), b.desc) for b in stmt.order_by]

    # ---- order / limit
    def limit_val(e):
        if e is None:
            return None
        if isinstance(e, A.Literal) and e.kind in ("int", "bool"):
            return int(e.value)
        if isinstance(e, int):
            return e
        raise PlanError("LIMIT expects integer literals")

    limit_n = offset_n = None
    if stmt.limit is not None:
        limit_n = limit_val(stmt.limit.count)
        offset_n = limit_val(stmt.limit.offset) or 0
    if order_items:
        if limit_n is not None:
            executors.append(TopN(order_by=tuple(order_items), limit=limit_n + offset_n))
        else:
            # ORDER BY without LIMIT: a REAL full sort — every row comes
            # back in order (the r2 2^20 TopN truncation trap is gone;
            # ref: sortexec/sort.go)
            executors.append(Sort(order_by=tuple(order_items)))
    elif limit_n is not None:
        executors.append(Limit(limit_n + offset_n))

    # ---- projection / offsets
    from ..expr.ir import ColumnRef

    if all(isinstance(e, ColumnRef) for e in out_exprs):
        offsets = tuple(e.index for e in out_exprs)
    else:
        executors.append(Projection(tuple(out_exprs)))
        offsets = tuple(range(len(out_exprs)))

    dag = DAGRequest(tuple(executors), output_offsets=offsets)
    return PlannedQuery(
        dag, probe_meta, build_tables, names,
        offset=offset_n or 0, ranges=scan_ranges, access_path=access_path,
        range_src=range_src,
        lookup=lookup,
        lookup_merge=lookup_merge,
        small_groups=_ndv_group_hint(dag, trefs, catalog),
    )


def _ndv_group_hint(dag: DAGRequest, trefs: list, catalog: Catalog, cap: int = 512) -> int | None:
    """NDV-product few-groups hint (ref: the reference's stats-driven agg
    mode choice; cmsketch.go/histogram NDV feeding cardinality): when every
    GROUP BY key is a bare column with ANALYZE stats, the product of the
    column NDVs bounds the group count."""
    from ..expr.ir import ColumnRef

    agg = dag.executors[-1] if dag.executors else None
    if not isinstance(agg, Aggregation) or not agg.group_by:
        return None
    product = 1
    for g in agg.group_by:
        if not isinstance(g, ColumnRef):
            return None
        cm = None
        for tr in trefs:
            if tr.offset <= g.index < tr.offset + len(tr.meta.columns):
                cm = tr.meta.columns[g.index - tr.offset]
                tstats = catalog.stats.get(tr.meta.table_id)
                break
        else:
            return None
        cs = tstats.columns.get(cm.name) if tstats is not None else None
        if cs is None or cs.ndv <= 0:
            return None
        product *= cs.ndv + (1 if cs.null_count else 0)
        if product > cap:
            return None
    c = 16
    while c < product:
        c *= 2
    return c
