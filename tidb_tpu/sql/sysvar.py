"""System variables (ref: pkg/sessionctx/variable/sysvar.go — 456 vars with
scopes and validators; this registry carries the subset the engine consults,
including the TPU backend's feature gate, which follows the
TiDBAllowMPPExecution pattern at sysvar.go:1910)."""

from __future__ import annotations

from dataclasses import dataclass, field


class SysVarError(ValueError):
    pass


def _bool_validator(v: str) -> str:
    t = v.strip().upper()
    if t in ("ON", "1", "TRUE"):
        return "ON"
    if t in ("OFF", "0", "FALSE"):
        return "OFF"
    raise SysVarError(f"expected ON/OFF, got {v!r}")


def _enum_validator(*allowed: str):
    def check(v: str) -> str:
        t = v.strip().lower()
        if t not in allowed:
            raise SysVarError(f"expected one of {allowed}, got {v!r}")
        return t

    return check


def _snapshot_validator(v: str) -> str:
    t = v.strip()
    if t and not t.isdigit():
        raise SysVarError("tidb_snapshot expects a TSO timestamp (or '' to clear)")
    return t


# the engines THIS build actually has: the TPU row store and the HTAP
# columnar replica. The reference's engine names are accepted as aliases
# and normalized (tikv/tidb -> the row store, tiflash -> columnar), so
# reference-tuned `SET tidb_isolation_read_engines = 'tikv,tiflash'`
# statements keep working (ref: sysvar.go TiDBIsolationReadEngines
# validation against config.IsolationRead.Engines).
_ENGINE_ALIASES = {
    "tpu": "tpu", "tikv": "tpu", "tidb": "tpu",
    "columnar": "columnar", "tiflash": "columnar",
}


def _engines_validator(v: str) -> str:
    names = [t.strip().lower() for t in v.split(",") if t.strip()]
    if not names:
        raise SysVarError(
            "tidb_isolation_read_engines needs at least one engine (tpu, columnar)")
    out: list = []
    for n in names:
        e = _ENGINE_ALIASES.get(n)
        if e is None:
            raise SysVarError(
                f"unknown isolation read engine {n!r} (this build has: tpu, "
                f"columnar; tikv/tidb/tiflash accepted as aliases)")
        if e not in out:
            out.append(e)
    return ",".join(out)


def _int_validator(lo: int, hi: int):
    def check(v: str) -> str:
        try:
            n = int(v)
        except ValueError as exc:
            raise SysVarError(f"expected integer, got {v!r}") from exc
        if not (lo <= n <= hi):
            raise SysVarError(f"value {n} out of range [{lo}, {hi}]")
        return str(n)

    return check


@dataclass
class SysVar:
    name: str
    default: str
    scope: str = "session"  # session | global | both
    validator: object = None

    def validate(self, v: str) -> str:
        return self.validator(v) if self.validator else v


DEFINITIONS = {
    v.name: v
    for v in [
        # the TPU coprocessor gate (ref: TiDBAllowMPPExecution pattern)
        SysVar("tidb_enable_tpu_coprocessor", "ON", "both", _bool_validator),
        # route eligible GROUP BY plans over the device mesh (Partial1 ->
        # all_to_all exchange -> Final); needs >= 2 devices at runtime
        # (ref: TiDBAllowMPPExecution / enforce-mpp engine selection)
        SysVar("tidb_enable_tpu_mesh", "ON", "both", _bool_validator),
        # the MPP tier above the mesh (ISSUE 18): plan eligible statements
        # as exchange-linked fragment graphs (mpp/fragment.py) dispatched
        # through the wire seam, probe scans served from the columnar
        # replica when it covers the snapshot. OFF falls back to the
        # whole-plan mesh shortcut (ref: sysvar.go TiDBAllowMPPExecution)
        SysVar("tidb_allow_mpp", "ON", "both", _bool_validator),
        # data-size floor for the mesh DISPATCH tier (distsql/planner.py):
        # below this estimated row count the vmapped batch tier serves
        SysVar("tidb_tpu_mesh_min_rows", "0", "both", _int_validator(0, 1 << 40)),
        # ref: sysvar.go:1956 TiDBDistSQLScanConcurrency
        SysVar("tidb_distsql_scan_concurrency", "4", "both", _int_validator(1, 256)),
        # ref: sysvar.go:2080 TiDBMaxChunkSize
        SysVar("tidb_max_chunk_size", "1024", "both", _int_validator(32, 1 << 20)),
        SysVar("tidb_mem_quota_query", str(1 << 30), "both", _int_validator(0, 1 << 60)),
        SysVar("tidb_enable_paging", "OFF", "both", _bool_validator),
        # ref: sysvar.go TiDBAllowBatchCop (regions-per-store batching)
        SysVar("tidb_allow_batch_cop", "OFF", "both", _bool_validator),
        # ref: sysvar.go TiDBReplicaRead — which peer of a region serves
        # reads: the leader (default), a follower whose safe_ts covers the
        # snapshot, or the least-loaded peer ("closest")
        SysVar("tidb_replica_read", "leader", "both",
               _enum_validator("leader", "follower", "closest-replica")),
        SysVar("tidb_opt_agg_push_down", "ON", "both", _bool_validator),
        SysVar("autocommit", "ON", "both", _bool_validator),
        # ref: sysvar.go TiDBTxnMode (pessimistic is TiDB's default)
        SysVar("tidb_txn_mode", "pessimistic", "both", _enum_validator("pessimistic", "optimistic")),
        # ref: sysvar.go CTEMaxRecursionDepth
        SysVar("cte_max_recursion_depth", "1000", "both", _int_validator(0, 1 << 20)),
        SysVar("sql_mode", "STRICT_TRANS_TABLES", "both"),
        SysVar("time_zone", "UTC", "both"),
        # ---- engine knobs wired into real code paths -------------------
        # starting group-table capacity for device group-by (the overflow
        # retry quadruples from here; exec/builder.py DEFAULT_GROUP_CAPACITY)
        SysVar("tidb_tpu_group_capacity", "4096", "both", _int_validator(16, 1 << 24)),
        # MySQL: implicit LIMIT on top-level SELECT results (sql_select_limit)
        SysVar("sql_select_limit", str((1 << 64) - 1), "both", _int_validator(0, (1 << 64) - 1)),
        # ref: sysvar.go TiDBSnapshot — stale read: session reads rewind to
        # this TSO (session.py _read_ts) and writes are rejected while set
        SysVar("tidb_snapshot", "", "session", _snapshot_validator),
        # ---- planner/executor toggles the reference exposes ------------
        # (ref: pkg/sessionctx/variable/sysvar.go — same names; accepted
        # and visible via SELECT @@/SHOW VARIABLES; ones without a matching
        # code path here validate + round-trip but do not change behavior,
        # exactly like the reference's noop-sysvars list sysvar.go's
        # SetNoopVars)
        SysVar("tidb_cost_model_version", "2", "both", _int_validator(1, 2)),
        # MySQL: group_concat result truncation length
        SysVar("group_concat_max_len", "1024", "both", _int_validator(4, 1 << 30)),
        # MySQL: decimal division scale increment (ref: cop_handler.go:350;
        # the expression compiler currently fixes the increment at 4)
        SysVar("div_precision_increment", "4", "both", _int_validator(0, 30)),
        SysVar("tidb_enable_vectorized_expression", "ON", "both", _bool_validator),
        SysVar("tidb_opt_insubq_to_join_and_agg", "ON", "both", _bool_validator),
        SysVar("tidb_partition_prune_mode", "dynamic", "both", _enum_validator("static", "dynamic")),
        SysVar("tidb_hashagg_partial_concurrency", "-1", "both", _int_validator(-1, 256)),
        SysVar("tidb_hashagg_final_concurrency", "-1", "both", _int_validator(-1, 256)),
        SysVar("tidb_hash_join_concurrency", "-1", "both", _int_validator(-1, 256)),
        SysVar("tidb_projection_concurrency", "-1", "both", _int_validator(-1, 256)),
        SysVar("tidb_window_concurrency", "-1", "both", _int_validator(-1, 256)),
        SysVar("tidb_executor_concurrency", "5", "both", _int_validator(1, 256)),
        SysVar("tidb_index_lookup_concurrency", "-1", "both", _int_validator(-1, 256)),
        SysVar("tidb_index_serial_scan_concurrency", "1", "both", _int_validator(1, 256)),
        SysVar("tidb_build_stats_concurrency", "4", "both", _int_validator(1, 256)),
        SysVar("tidb_enable_outer_join_reorder", "ON", "both", _bool_validator),
        SysVar("tidb_enable_index_merge", "ON", "both", _bool_validator),
        SysVar("tidb_enable_window_function", "ON", "both", _bool_validator),
        SysVar("tidb_enable_null_aware_anti_join", "ON", "both", _bool_validator),
        SysVar("tidb_enable_unsafe_substitute", "OFF", "both", _bool_validator),
        SysVar("tidb_enable_clustered_index", "ON", "both"),
        SysVar("tidb_analyze_version", "2", "both", _int_validator(1, 2)),
        SysVar("tidb_enable_chunk_rpc", "ON", "session", _bool_validator),
        # which engines may serve reads (ref: sysvar.go
        # TiDBIsolationReadEngines): the tpu row store and/or the HTAP
        # columnar replica — validated at SET time, reference names
        # normalized, unknown names rejected (ISSUE 12 satellite)
        SysVar("tidb_isolation_read_engines", "tpu,columnar", "both", _engines_validator),
        SysVar("tidb_opt_correlation_threshold", "0.9", "both"),
        SysVar("tidb_opt_limit_push_down_threshold", "100", "both", _int_validator(0, 1 << 30)),
        SysVar("tidb_opt_distinct_agg_push_down", "OFF", "both", _bool_validator),
        SysVar("tidb_retry_limit", "10", "both", _int_validator(0, 1 << 20)),
        SysVar("tidb_backoff_weight", "2", "both", _int_validator(0, 1 << 20)),
        SysVar("tidb_row_format_version", "2", "global", _int_validator(1, 2)),
        SysVar("tidb_slow_log_threshold", "300", "both", _int_validator(-1, 1 << 30)),
        SysVar("tidb_enable_slow_log", "ON", "both", _bool_validator),
        SysVar("tidb_stmt_summary_max_stmt_count", "3000", "global", _int_validator(1, 1 << 20)),
        SysVar("tidb_enable_stmt_summary", "ON", "both", _bool_validator),
        # ---- Top SQL (ISSUE 17; ref: tidb_enable_top_sql +
        # tidb_top_sql_max_statement_count, sysvar.go) — per-digest
        # CPU+device attribution; OFF skips tagging entirely so a
        # statement pays one sysvar read and nothing else
        SysVar("tidb_enable_top_sql", "ON", "both", _bool_validator),
        # top-K digests each reporter window retains per metric before
        # the "(others)" fold (ref default 200; scaled to in-process)
        SysVar("tidb_top_sql_max_statement_count", "30", "both", _int_validator(1, 5000)),
        # ---- production front door (ISSUE 15) --------------------------
        # digest-keyed plan cache (ref: tidb_enable_prepared_plan_cache +
        # the non-prepared plan cache, sysvar.go): repeated statements
        # re-bind literals into a cached template, skipping parse+plan
        SysVar("tidb_enable_plan_cache", "ON", "both", _bool_validator),
        # LRU capacity of the instance plan cache (ref:
        # tidb_session_plan_cache_size)
        SysVar("tidb_plan_cache_size", "512", "both", _int_validator(1, 1 << 20)),
        # per-SESSION memory quota parenting every query tracker (0 =
        # unlimited; ref: the server/session tracker tree in util/memory)
        SysVar("tidb_mem_quota_session", "0", "both", _int_validator(0, 1 << 60)),
        # ---- cross-session fused execution (ISSUE 19) ------------------
        # coalesce concurrent plan-cache-hit point gets into one batched
        # device launch and autocommit single-row writes into group
        # commits (OFF: every statement launches/proposes alone)
        SysVar("tidb_tpu_enable_coalesce", "OFF", "both", _bool_validator),
        # micro-batch window: how long the first lane waits for company
        SysVar("tidb_tpu_coalesce_wait_us", "300", "both", _int_validator(0, 1_000_000)),
        # lane count that closes the window early
        SysVar("tidb_tpu_coalesce_max_lanes", "64", "both", _int_validator(1, 4096)),
        # autocommit writes above this mutation count skip group commit
        SysVar("tidb_tpu_coalesce_max_write_keys", "16", "both", _int_validator(1, 1024)),
        # publish/adopt plan-cache entries through the process-wide
        # cross-catalog tier (every shared hit fingerprint-revalidates)
        SysVar("tidb_tpu_plan_cache_shared", "OFF", "both", _bool_validator),
        # ---- MySQL-compatibility variables -----------------------------
        SysVar("transaction_isolation", "REPEATABLE-READ", "both",
               _enum_validator("read-uncommitted", "read-committed", "repeatable-read", "serializable")),
        SysVar("tx_isolation", "REPEATABLE-READ", "both"),
        SysVar("character_set_client", "utf8mb4", "both"),
        SysVar("character_set_connection", "utf8mb4", "both"),
        SysVar("character_set_results", "utf8mb4", "both"),
        SysVar("character_set_database", "utf8mb4", "both"),
        SysVar("collation_connection", "utf8mb4_bin", "both"),
        SysVar("collation_database", "utf8mb4_bin", "both"),
        SysVar("default_collation_for_utf8mb4", "utf8mb4_bin", "both"),
        SysVar("foreign_key_checks", "ON", "both", _bool_validator),
        SysVar("block_encryption_mode", "aes-128-ecb", "both"),
        SysVar("max_execution_time", "0", "both", _int_validator(0, 1 << 31)),
        SysVar("wait_timeout", "28800", "both", _int_validator(0, 1 << 31)),
        SysVar("interactive_timeout", "28800", "both", _int_validator(1, 1 << 31)),
        SysVar("max_allowed_packet", str(64 << 20), "both", _int_validator(1024, 1 << 30)),
        SysVar("sql_safe_updates", "OFF", "both", _bool_validator),
        SysVar("innodb_lock_wait_timeout", "50", "both", _int_validator(1, 3600)),
        SysVar("version_comment", "TiDB-TPU", "global"),
        SysVar("last_insert_id", "0", "session", _int_validator(0, (1 << 64) - 1)),
    ]
}


def is_bool(name: str) -> bool:
    """Boolean-typed sysvars render 1/0 under SELECT @@x (MySQL prints the
    numeric form there; SHOW VARIABLES keeps ON/OFF)."""
    d = DEFINITIONS.get(name.lower())
    return d is not None and d.validator is _bool_validator


class SysVarStore:
    """Per-session values over the shared definitions."""

    def __init__(self):
        self._values: dict[str, str] = {}

    def get(self, name: str) -> str:
        name = name.lower()
        if name in self._values:
            return self._values[name]
        d = DEFINITIONS.get(name)
        if d is None:
            raise SysVarError(f"unknown system variable {name!r}")
        return d.default

    def get_bool(self, name: str) -> bool:
        return self.get(name) == "ON"

    def get_int(self, name: str) -> int:
        return int(self.get(name))

    def set(self, name: str, value: str):
        name = name.lower()
        d = DEFINITIONS.get(name)
        if d is None:
            raise SysVarError(f"unknown system variable {name!r}")
        self._values[name] = d.validate(str(value))

    def items(self):
        out = {name: d.default for name, d in DEFINITIONS.items()}
        out.update(self._values)
        return sorted(out.items())
