"""System variables (ref: pkg/sessionctx/variable/sysvar.go — 456 vars with
scopes and validators; this registry carries the subset the engine consults,
including the TPU backend's feature gate, which follows the
TiDBAllowMPPExecution pattern at sysvar.go:1910)."""

from __future__ import annotations

from dataclasses import dataclass, field


class SysVarError(ValueError):
    pass


def _bool_validator(v: str) -> str:
    t = v.strip().upper()
    if t in ("ON", "1", "TRUE"):
        return "ON"
    if t in ("OFF", "0", "FALSE"):
        return "OFF"
    raise SysVarError(f"expected ON/OFF, got {v!r}")


def _enum_validator(*allowed: str):
    def check(v: str) -> str:
        t = v.strip().lower()
        if t not in allowed:
            raise SysVarError(f"expected one of {allowed}, got {v!r}")
        return t

    return check


def _int_validator(lo: int, hi: int):
    def check(v: str) -> str:
        try:
            n = int(v)
        except ValueError as exc:
            raise SysVarError(f"expected integer, got {v!r}") from exc
        if not (lo <= n <= hi):
            raise SysVarError(f"value {n} out of range [{lo}, {hi}]")
        return str(n)

    return check


@dataclass
class SysVar:
    name: str
    default: str
    scope: str = "session"  # session | global | both
    validator: object = None

    def validate(self, v: str) -> str:
        return self.validator(v) if self.validator else v


DEFINITIONS = {
    v.name: v
    for v in [
        # the TPU coprocessor gate (ref: TiDBAllowMPPExecution pattern)
        SysVar("tidb_enable_tpu_coprocessor", "ON", "both", _bool_validator),
        # route eligible GROUP BY plans over the device mesh (Partial1 ->
        # all_to_all exchange -> Final); needs >= 2 devices at runtime
        # (ref: TiDBAllowMPPExecution / enforce-mpp engine selection)
        SysVar("tidb_enable_tpu_mesh", "ON", "both", _bool_validator),
        # ref: sysvar.go:1956 TiDBDistSQLScanConcurrency
        SysVar("tidb_distsql_scan_concurrency", "4", "both", _int_validator(1, 256)),
        # ref: sysvar.go:2080 TiDBMaxChunkSize
        SysVar("tidb_max_chunk_size", "1024", "both", _int_validator(32, 1 << 20)),
        SysVar("tidb_mem_quota_query", str(1 << 30), "both", _int_validator(0, 1 << 60)),
        SysVar("tidb_enable_paging", "OFF", "both", _bool_validator),
        # ref: sysvar.go TiDBAllowBatchCop (regions-per-store batching)
        SysVar("tidb_allow_batch_cop", "OFF", "both", _bool_validator),
        SysVar("tidb_opt_agg_push_down", "ON", "both", _bool_validator),
        SysVar("autocommit", "ON", "both", _bool_validator),
        # ref: sysvar.go TiDBTxnMode (pessimistic is TiDB's default)
        SysVar("tidb_txn_mode", "pessimistic", "both", _enum_validator("pessimistic", "optimistic")),
        # ref: sysvar.go CTEMaxRecursionDepth
        SysVar("cte_max_recursion_depth", "1000", "both", _int_validator(0, 1 << 20)),
        SysVar("sql_mode", "STRICT_TRANS_TABLES", "both"),
        SysVar("time_zone", "UTC", "both"),
    ]
}


class SysVarStore:
    """Per-session values over the shared definitions."""

    def __init__(self):
        self._values: dict[str, str] = {}

    def get(self, name: str) -> str:
        name = name.lower()
        if name in self._values:
            return self._values[name]
        d = DEFINITIONS.get(name)
        if d is None:
            raise SysVarError(f"unknown system variable {name!r}")
        return d.default

    def get_bool(self, name: str) -> bool:
        return self.get(name) == "ON"

    def get_int(self, name: str) -> int:
        return int(self.get(name))

    def set(self, name: str, value: str):
        name = name.lower()
        d = DEFINITIONS.get(name)
        if d is None:
            raise SysVarError(f"unknown system variable {name!r}")
        self._values[name] = d.validate(str(value))

    def items(self):
        out = {name: d.default for name, d in DEFINITIONS.items()}
        out.update(self._values)
        return sorted(out.items())
