"""Extension registry: custom scalar functions and system variables without
touching core (ref: pkg/extension — WithCustomSysVariables manifest.go:38,
WithCustomFunctions manifest.go:52; SURVEY §2.1 names this as the hook the
TPU feature gate itself would use in the reference).

Custom functions run host-side: the planner lowers them to IR ops, the
row-at-a-time evaluator dispatches to the registered Python callable, and
the DAG splitter keeps any expression containing one on the root side
(where the oracle fallback executes), exactly like a non-pushdown-able
builtin behind the pushdown blocklist (infer_pushdown.go IsPushDownEnabled).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..expr import ir
from ..types import Datum, DatumKind, FieldType, MyDecimal, new_double, new_longlong, new_varchar


@dataclass
class CustomFunction:
    name: str
    fn: object  # (*python values | None) -> python value | None
    ft: FieldType
    raw: bool = False  # fn takes the Datum list and returns a Datum
    # (internal consumers like the subquery Apply fallback need exact
    # types for bindings; user extensions keep the plain-value contract)


_APPLY_CAP = 256  # FIFO bound on internal __apply_* registrations


class ExtensionRegistry:
    def __init__(self):
        self.functions: dict[str, CustomFunction] = {}
        self._apply_fifo: list[str] = []

    def register_function(self, name: str, fn, result_ft: FieldType | None = None, raw: bool = False):
        """Register a host-evaluated scalar function usable from SQL.
        `fn` receives plain Python values (None for NULL) and returns one;
        the result type defaults to VARCHAR unless given. raw=True passes
        and returns Datums verbatim (internal use)."""
        name = name.lower()
        if name in ir.SCALAR_OPS:
            raise ValueError(f"{name!r} is a builtin and cannot be overridden")
        cf = CustomFunction(name, fn, result_ft or new_varchar(255), raw)
        self.functions[name] = cf
        ir.EXTENSION_OPS.add(name)
        if name.startswith("__apply_"):
            # the subquery Apply fallback registers one closure per
            # rewritten statement (it pins the sub-AST + result cache);
            # statements re-rewrite on every execution, so old entries are
            # dead — a FIFO cap keeps the registry bounded
            self._apply_fifo.append(name)
            if len(self._apply_fifo) > _APPLY_CAP:
                self.unregister_function(self._apply_fifo.pop(0))
        return cf

    def register_sysvar(self, name: str, default: str, validator=None, scope: str = "both"):
        """Register a custom system variable (ref: WithCustomSysVariables)."""
        from .sysvar import DEFINITIONS, SysVar

        name = name.lower()
        if name in DEFINITIONS:
            raise ValueError(f"sysvar {name!r} already defined")
        sv = SysVar(name, default, scope, validator)
        DEFINITIONS[name] = sv
        return sv

    def unregister_function(self, name: str):
        self.functions.pop(name.lower(), None)
        ir.EXTENSION_OPS.discard(name.lower())

    def call(self, name: str, datums: list) -> Datum:
        cf = self.functions[name.lower()]
        if cf.raw:
            return cf.fn(list(datums))
        args = [None if d.is_null() else d.val for d in datums]
        out = cf.fn(*args)
        return _to_datum(out, cf.ft)


def _to_datum(v, ft: FieldType) -> Datum:
    if v is None:
        return Datum.NULL
    if isinstance(v, bool):
        return Datum.i64(int(v))
    if isinstance(v, int):
        return Datum.u64(v) if ft.is_unsigned() else Datum.i64(v)
    if isinstance(v, float):
        return Datum.f64(v)
    if isinstance(v, MyDecimal):
        return Datum.dec(v)
    if isinstance(v, bytes):
        return Datum.bytes_(v)
    return Datum.string(str(v))


EXTENSIONS = ExtensionRegistry()
