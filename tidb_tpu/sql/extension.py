"""Extension registry: custom scalar functions and system variables without
touching core (ref: pkg/extension — WithCustomSysVariables manifest.go:38,
WithCustomFunctions manifest.go:52; SURVEY §2.1 names this as the hook the
TPU feature gate itself would use in the reference).

Custom functions run host-side: the planner lowers them to IR ops, the
row-at-a-time evaluator dispatches to the registered Python callable, and
the DAG splitter keeps any expression containing one on the root side
(where the oracle fallback executes), exactly like a non-pushdown-able
builtin behind the pushdown blocklist (infer_pushdown.go IsPushDownEnabled).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..expr import ir
from ..types import Datum, DatumKind, FieldType, MyDecimal, new_double, new_longlong, new_varchar


@dataclass
class CustomFunction:
    name: str
    fn: object  # (*python values | None) -> python value | None
    ft: FieldType


class ExtensionRegistry:
    def __init__(self):
        self.functions: dict[str, CustomFunction] = {}

    def register_function(self, name: str, fn, result_ft: FieldType | None = None):
        """Register a host-evaluated scalar function usable from SQL.
        `fn` receives plain Python values (None for NULL) and returns one;
        the result type defaults to VARCHAR unless given."""
        name = name.lower()
        if name in ir.SCALAR_OPS:
            raise ValueError(f"{name!r} is a builtin and cannot be overridden")
        cf = CustomFunction(name, fn, result_ft or new_varchar(255))
        self.functions[name] = cf
        ir.EXTENSION_OPS.add(name)
        return cf

    def register_sysvar(self, name: str, default: str, validator=None, scope: str = "both"):
        """Register a custom system variable (ref: WithCustomSysVariables)."""
        from .sysvar import DEFINITIONS, SysVar

        name = name.lower()
        if name in DEFINITIONS:
            raise ValueError(f"sysvar {name!r} already defined")
        sv = SysVar(name, default, scope, validator)
        DEFINITIONS[name] = sv
        return sv

    def unregister_function(self, name: str):
        self.functions.pop(name.lower(), None)
        ir.EXTENSION_OPS.discard(name.lower())

    def call(self, name: str, datums: list) -> Datum:
        cf = self.functions[name.lower()]
        args = [None if d.is_null() else d.val for d in datums]
        out = cf.fn(*args)
        return _to_datum(out, cf.ft)


def _to_datum(v, ft: FieldType) -> Datum:
    if v is None:
        return Datum.NULL
    if isinstance(v, bool):
        return Datum.i64(int(v))
    if isinstance(v, int):
        return Datum.u64(v) if ft.is_unsigned() else Datum.i64(v)
    if isinstance(v, float):
        return Datum.f64(v)
    if isinstance(v, MyDecimal):
        return Datum.dec(v)
    if isinstance(v, bytes):
        return Datum.bytes_(v)
    return Datum.string(str(v))


EXTENSIONS = ExtensionRegistry()
