"""Predicate -> key ranges (ref: pkg/util/ranger — range building from
WHERE conjuncts for the planner's access-path selection).

Extracts intervals on a single column from eq/lt/le/gt/ge/BETWEEN/IN
conjuncts, intersects them, and renders either integer handle ranges
(primary-key pruning: scan fewer rows) or memcomparable index key ranges."""

from __future__ import annotations

from dataclasses import dataclass

from ..codec import tablecodec
from ..codec.datum_codec import encode_datum
from ..parser import ast as A
from ..types import Datum, DatumKind

I64_MIN = -(1 << 63)
I64_MAX = (1 << 63) - 1


@dataclass
class Interval:
    """One [low, high] interval over Datums; None bound = unbounded."""

    low: object = None  # Datum | None
    high: object = None
    low_inc: bool = True
    high_inc: bool = True


def _is_col(e, name: str) -> bool:
    return isinstance(e, A.ColumnName) and e.name.lower() == name


def _const_datum(e, eval_const) -> Datum | None:
    if isinstance(e, A.Literal) and e.kind != "null":
        return eval_const(e)  # may be None: lossy coercion declined
    return None


_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def intervals_for_column(conjuncts: list, col_name: str, eval_const) -> list | None:
    """Intervals implied by the conjuncts on `col_name`, or None when the
    conjuncts don't constrain it. eval_const: Literal AST -> Datum.

    Each usable conjunct contributes an interval set; sets intersect.
    Non-matching conjuncts are ignored (they remain as filters)."""
    sets: list[list[Interval]] = []
    for c in conjuncts:
        got = _conjunct_intervals(c, col_name, eval_const)
        if got is not None:
            sets.append(got)
    if not sets:
        return None
    out = sets[0]
    for s in sets[1:]:
        out = _intersect(out, s)
        if not out:
            return []  # provably empty
    return _merge(out)


def _merge(ivs: list) -> list:
    """Sort and merge overlapping intervals so no key range is emitted
    twice (IN (5,5) must not scan the row twice)."""

    # sort: unbounded lows first, then by low value, inclusive before exclusive
    def sort_key(iv):
        if iv.low is None:
            return (0, 0, 0)
        return (1, _SortDatum(iv.low), 0 if iv.low_inc else 1)

    ivs = sorted(ivs, key=sort_key)
    out: list = [ivs[0]]
    for iv in ivs[1:]:
        last = out[-1]
        # does iv start within (or adjacent-inclusively to) last?
        overlaps = last.high is None
        if not overlaps and iv.low is not None:
            c = _cmp(iv.low, last.high)
            overlaps = c < 0 or (c == 0 and (iv.low_inc or last.high_inc))
        elif not overlaps:
            overlaps = True  # iv.low unbounded
        if overlaps:
            # extend last.high if iv reaches further
            if last.high is not None and (
                iv.high is None or _cmp(iv.high, last.high) > 0 or (_cmp(iv.high, last.high) == 0 and iv.high_inc)
            ):
                out[-1] = Interval(last.low, iv.high, last.low_inc, iv.high_inc)
        else:
            out.append(iv)
    return out


class _SortDatum:
    """Orderable wrapper over Datum for interval sorting."""

    __slots__ = ("d",)

    def __init__(self, d):
        self.d = d

    def __lt__(self, other):
        return _cmp(self.d, other.d) < 0

    def __eq__(self, other):
        return _cmp(self.d, other.d) == 0


def _conjunct_intervals(c, col_name: str, eval_const) -> list | None:
    if isinstance(c, A.BinaryOp) and c.op in _FLIP:
        if _is_col(c.left, col_name):
            d = _const_datum(c.right, eval_const)
            op = c.op
        elif _is_col(c.right, col_name):
            d = _const_datum(c.left, eval_const)
            op = _FLIP[c.op]
        else:
            return None
        if d is None:
            return None
        if op == "eq":
            return [Interval(d, d)]
        if op == "lt":
            return [Interval(None, d, high_inc=False)]
        if op == "le":
            return [Interval(None, d)]
        if op == "gt":
            return [Interval(d, None, low_inc=False)]
        return [Interval(d, None)]
    if isinstance(c, A.Between) and not c.negated and _is_col(c.expr, col_name):
        lo, hi = _const_datum(c.low, eval_const), _const_datum(c.high, eval_const)
        if lo is None or hi is None:
            return None
        return [Interval(lo, hi)]
    if isinstance(c, A.InList) and not c.negated and _is_col(c.expr, col_name):
        ds = [_const_datum(i, eval_const) for i in c.items]
        if any(d is None for d in ds):
            return None
        return [Interval(d, d) for d in ds]
    return None


def _cmp(a: Datum, b: Datum) -> int:
    from ..expr.eval_ref import compare

    return compare(a, b)


def _tighter_low(l1, i1, l2, i2):
    if l1 is None:
        return l2, i2
    if l2 is None:
        return l1, i1
    c = _cmp(l2, l1)
    if c > 0:
        return l2, i2
    if c < 0:
        return l1, i1
    return l1, i1 and i2


def _tighter_high(h1, i1, h2, i2):
    if h1 is None:
        return h2, i2
    if h2 is None:
        return h1, i1
    c = _cmp(h2, h1)
    if c < 0:
        return h2, i2
    if c > 0:
        return h1, i1
    return h1, i1 and i2


def _intersect(xs: list, ys: list) -> list:
    out = []
    for x in xs:
        for y in ys:
            lo, lo_inc = _tighter_low(x.low, x.low_inc, y.low, y.low_inc)
            hi, hi_inc = _tighter_high(x.high, x.high_inc, y.high, y.high_inc)
            if lo is not None and hi is not None:
                c = _cmp(lo, hi)
                if c > 0 or (c == 0 and not (lo_inc and hi_inc)):
                    continue
            out.append(Interval(lo, hi, lo_inc, hi_inc))
    return out


def handle_ranges_from_intervals(table_id: int, intervals: list) -> list:
    """Integer intervals -> row-key ranges (PK handle pruning)."""
    from ..store.store import KeyRange

    out = []
    for iv in intervals:
        lo = I64_MIN
        if iv.low is not None:
            lo = int(iv.low.val) + (0 if iv.low_inc else 1)
        hi = I64_MAX
        if iv.high is not None:
            hi = int(iv.high.val) - (0 if iv.high_inc else 1)
        if lo > hi:
            continue
        out.append(KeyRange(tablecodec.encode_row_key(table_id, lo), tablecodec.encode_row_key(table_id, hi) + b"\x00"))
    return out


def index_ranges_from_intervals(table_id: int, index_id: int, intervals: list) -> list:
    """First-index-column intervals -> index key ranges. Exclusive bounds
    append 0xff past the encoded datum (encoded datums are self-delimiting,
    and any key continuing an equal first column sorts below it)."""
    from ..store.store import KeyRange

    prefix = tablecodec.encode_index_key(table_id, index_id, [])
    out = []
    for iv in intervals:
        if iv.low is None:
            start = prefix
        else:
            start = prefix + encode_datum(iv.low) + (b"" if iv.low_inc else b"\xff")
        if iv.high is None:
            end = prefix + b"\xff"
        else:
            end = prefix + encode_datum(iv.high) + (b"\xff" if iv.high_inc else b"")
        if start < end:
            out.append(KeyRange(start, end))
    return out
