"""Subquery materialization + decorrelation — the session-side rewrite pass
that removes every subquery construct from a SELECT before planning.

The reference splits this between the expression rewriter (uncorrelated
subqueries evaluate during plan building, pkg/planner/core/expression_rewriter.go)
and the decorrelation rule (correlated IN/EXISTS become semi/anti
LogicalJoins, correlated scalar aggregates become outer joins over a
re-grouped inner — pkg/planner/core/rule_decorrelate.go). Here both shapes
land on the same mechanism: the inner query is *materialized* into an
in-memory table (`MatRegistry`) that the planner sees through its `mat`
namespace, and the outer AST is rewritten to reference it:

  uncorrelated scalar          -> datum literal
  uncorrelated EXISTS          -> 0/1 literal (inner runs with LIMIT 1)
  uncorrelated IN, small       -> InList of datum literals (exact 3VL)
  uncorrelated IN, large       -> SemiJoinCond against the materialized rows
  cmp ANY/ALL (uncorrelated)   -> min/max comparison with empty/NULL guards
  correlated [NOT] IN / EXISTS -> SemiJoinCond (semi/anti join in the DAG)
  correlated scalar (agg)      -> LEFT JOIN of the inner re-grouped by its
                                  correlation keys + column reference
  anything decorrelation can't -> Apply fallback: a host-evaluated function
                                  re-executes the subquery per outer row
                                  with the outer references bound — the
                                  analog of the LogicalApply operator the
                                  reference keeps when pull-up fails
                                  (rule_decorrelate.go); exact 3VL for
                                  (NOT) IN incl. row-value probes (the
                                  null-aware anti-join semantics,
                                  ref: pkg/planner/core/exhaust_physical_plans.go NAAJ)

CTEs (including recursive ones) materialize here too and shadow catalog
tables by name (ref: pkg/planner/core/logical_plan_builder.go buildWith).
"""

from __future__ import annotations

import copy
import itertools

from ..chunk import Chunk
from ..exec.executor import datum_group_key
from ..expr.eval_ref import compare
from ..parser import ast as A
from ..types import Datum
from .catalog import Catalog, ColumnMeta, TableMeta

# IN-lists up to this size inline as literals (one fused compare chain on
# device); larger sets become semi joins against the materialized rows
MAX_IN_LITERALS = 64


def _probe_items(expr) -> list:
    """IN-probe component expressions: (a, b) row values flatten."""
    return list(expr.items) if isinstance(expr, A.RowExpr) else [expr]


class SubqueryError(ValueError):
    pass


def _dlit(d: Datum) -> A.Literal:
    return A.Literal(d, "datum")


TRUE_LIT = lambda: A.Literal(1, "int")  # noqa: E731
FALSE_LIT = lambda: A.Literal(0, "int")  # noqa: E731
NULL_LIT = lambda: A.Literal(None, "null")  # noqa: E731


from .planner import _split_conjuncts  # shared conjunct splitting


def _and_all(conjs):
    out = None
    for c in conjs:
        out = c if out is None else A.BinaryOp("and", out, c)
    return out


class MatRegistry:
    """Materialized result sets, keyed by generated storage names ("#m<n>",
    never valid SQL identifiers). Negative table ids never collide with
    catalog tables and are assigned in registration order, so two statements
    with the same shape share the compiled-program cache (the DAG
    fingerprint includes the id). User-visible CTE names bind per rewriter
    scope (SubqueryRewriter.bindings), NOT here — a CTE inside a subquery
    must not shadow tables in the outer query."""

    def __init__(self):
        self.metas: dict[str, TableMeta] = {}
        self.chunks: dict[str, Chunk] = {}
        self._ids = itertools.count(1)

    def register(self, names, fts, rows) -> TableMeta:
        storage = f"#m{next(self._ids)}"
        used: set = set()
        cols = []
        for i, (n, ft) in enumerate(zip(names, fts)):
            base = (n or f"c{i}").lower()
            nm, k = base, 2
            while nm in used:
                nm, k = f"{base}_{k}", k + 1
            used.add(nm)
            cols.append(ColumnMeta(nm, i + 1, ft))
        meta = TableMeta(storage, -next(self._ids), cols, [], None)
        meta.row_count = len(rows)
        self.metas[storage] = meta
        self.chunks[storage] = Chunk.from_rows(list(fts), rows)
        return meta

    def update_rows(self, meta: TableMeta, rows) -> None:
        """Replace a registered table's rows (recursive-CTE iteration)."""
        meta.row_count = len(rows)
        self.chunks[meta.name] = Chunk.from_rows([c.ft for c in meta.columns], rows)


class SubqueryRewriter:
    """One statement's rewrite pass. `exec_query` runs a nested
    SelectStmt/SetOprStmt to (names, fts, rows) — the session wires it to
    its own executor with this rewriter as the parent so nested queries see
    enclosing CTE bindings (scoped, innermost wins) while materialized
    storage is shared."""

    def __init__(self, catalog: Catalog, registry: MatRegistry | None = None, max_recursion: int = 1000,
                 parent: "SubqueryRewriter | None" = None):
        self.catalog = catalog
        self.registry = registry or MatRegistry()
        self.max_recursion = max_recursion
        self.parent = parent
        self.bindings: dict[str, TableMeta] = {}  # CTE name -> meta (this scope)
        self.exec_query = None  # set by the session after construction

    def mat_dict(self) -> dict:
        """The planner's `mat` namespace for this scope: every storage
        entry (referenced by generated '#m…' names) plus the CTE bindings
        visible here (enclosing scopes first, this scope overriding)."""
        out = dict(self.parent.mat_dict()) if self.parent is not None else {}
        out.update(self.registry.metas)
        out.update(self.bindings)
        return out

    # ------------------------------------------------------------- schema
    def _table_cols(self, name: str) -> list | None:
        m = self.mat_dict().get(name.lower())
        if m is None:
            try:
                m = self.catalog.table(name)
            except Exception:
                return None
        return [c.name for c in m.columns]

    def _from_schema(self, node) -> list:
        """FROM tree -> [(alias, [colnames])]; None for unknown tables (the
        planner reports those with a proper error later)."""
        if node is None:
            return []
        if isinstance(node, A.TableName):
            cols = self._table_cols(node.name) or []
            return [((node.alias or node.name.rsplit(".", 1)[-1]).lower(), cols)]
        if isinstance(node, A.SubqueryTable):
            sel = node.subquery
            labels = []
            inner = sel.selects[0] if isinstance(sel, A.SetOprStmt) else sel
            fields = inner.fields
            inner_schema = None
            for f in fields:
                e = f.expr if isinstance(f, A.SelectField) else f
                if isinstance(e, A.Star):
                    # expand the star against the subquery's own FROM so the
                    # derived table's schema is complete for correlation checks
                    if inner_schema is None:
                        inner_schema = self._from_schema(inner.from_clause)
                    for alias, cols in inner_schema:
                        if e.table and alias != e.table.lower():
                            continue
                        labels.extend(cols)
                    continue
                if isinstance(f, A.SelectField) and f.alias:
                    labels.append(f.alias.lower())
                elif isinstance(e, A.ColumnName):
                    labels.append(e.name.lower())
            return [(node.alias.lower(), labels)]
        if isinstance(node, A.Join):
            return self._from_schema(node.left) + self._from_schema(node.right)
        return []

    @staticmethod
    def _resolves(c: A.ColumnName, schema: list) -> bool:
        if c.table:
            t = c.table.lower()
            return any(alias == t for alias, _ in schema)
        return any(c.name.lower() in cols for _, cols in schema)

    def _refs_outer(self, node, inner_schema: list, outer_scopes: list) -> bool:
        """Does any column under `node` resolve only in an enclosing scope?
        Nested subqueries extend the scope stack with their own FROM."""
        found = [False]

        def walk(n, schemas):
            if found[0] or not hasattr(n, "__dataclass_fields__"):
                return
            if isinstance(n, A.ColumnName):
                if not self._resolves(n, schemas[-1]) and any(self._resolves(n, s) for s in schemas[:-1]):
                    found[0] = True
                return
            sub = getattr(n, "subquery", None)
            if sub is not None and not isinstance(n, A.SubqueryTable):
                inner_sel = sub.selects[0] if isinstance(sub, A.SetOprStmt) else sub
                walk_stmt(inner_sel, schemas + [self._from_schema(inner_sel.from_clause)])
                # DON'T return: sibling fields (InSubquery.expr,
                # CompareSubquery.expr) can carry outer references of their
                # own (ADVICE r2: early return misclassified the enclosing
                # subquery as uncorrelated)
            for f_ in n.__dataclass_fields__:
                if f_ == "subquery":
                    continue  # handled above with the extended scope
                v = getattr(n, f_)
                for it in v if isinstance(v, (list, tuple)) else [v]:
                    if isinstance(it, tuple):
                        for x in it:
                            walk(x, schemas)
                    elif hasattr(it, "__dataclass_fields__"):
                        walk(it, schemas)

        def walk_stmt(sel, schemas):
            for f in sel.fields:
                walk(f, schemas)
            for part in (sel.where, sel.having):
                if part is not None:
                    walk(part, schemas)
            for b in list(sel.group_by) + list(sel.order_by):
                walk(b.expr, schemas)

        schemas = outer_scopes + [inner_schema]
        if isinstance(node, A.SelectStmt):
            walk_stmt(node, schemas)
            # join ON conditions can carry correlation too
            def walk_from(fr):
                if isinstance(fr, A.Join):
                    walk_from(fr.left)
                    walk_from(fr.right)
                    if fr.on is not None:
                        walk(fr.on, schemas)
            walk_from(node.from_clause)
        else:
            walk(node, schemas)
        return found[0]

    # ------------------------------------------------------- entry points
    def process_ctes(self, ctes: list) -> None:
        for cte in ctes:
            if cte.recursive and isinstance(cte.subquery, A.SetOprStmt):
                self._recursive_cte(cte)
                continue
            names, fts, rows = self.exec_query(cte.subquery)
            if cte.columns:
                names = list(cte.columns) + list(names[len(cte.columns):])
            self.bindings[cte.name.lower()] = self.registry.register(names, fts, rows)

    def _recursive_cte(self, cte: A.CTE) -> None:
        """Delta-based recursive CTE evaluation (ref: pkg/executor/cte.go —
        seed part, then the recursive part iterates over the previous
        iteration's rows until a fixpoint or the depth cap)."""
        sets = cte.subquery
        # a WITH clause on the CTE's own body (nested CTEs) materializes
        # first so the seed/recursive parts can read it; the binding lands
        # in this scope (slightly wider than MySQL's body-only scope, but
        # later same-name definitions simply rebind)
        if getattr(sets, "ctes", None):
            self.process_ctes(sets.ctes)
            sets.ctes = []

        def refs_cte(sel) -> bool:
            def in_from(fr):
                if isinstance(fr, A.TableName):
                    return fr.name.lower() == cte.name.lower()
                if isinstance(fr, A.Join):
                    return in_from(fr.left) or in_from(fr.right)
                if isinstance(fr, A.SubqueryTable):
                    inner = fr.subquery
                    sels = inner.selects if isinstance(inner, A.SetOprStmt) else [inner]
                    return any(refs_cte(s) for s in sels)
                return False

            return in_from(sel.from_clause)

        seeds = [s for s in sets.selects if not refs_cte(s)]
        recs = [s for s in sets.selects if refs_cte(s)]
        if not seeds or not recs:
            raise SubqueryError(f"recursive CTE {cte.name!r} needs seed and recursive parts")
        distinct = not all(sets.all_flags)

        names = fts = None
        total: list = []
        seen: set = set()
        for s in seeds:
            n_, f_, r_ = self.exec_query(s)
            if names is None:
                names, fts = n_, f_
            total.extend(r_)
        if distinct:
            dedup = []
            for r in total:
                k = tuple(datum_group_key(d, ft) for d, ft in zip(r, fts))
                if k not in seen:
                    seen.add(k)
                    dedup.append(r)
            total = dedup
        if cte.columns:
            names = list(cte.columns) + list(names[len(cte.columns):])
        cmeta = self.registry.register(names, fts, total)
        self.bindings[cte.name.lower()] = cmeta
        delta = total
        for _ in range(self.max_recursion + 1):
            if not delta:
                break
            # the recursive part reads the previous iteration's delta
            self.registry.update_rows(cmeta, delta)
            new: list = []
            for s in recs:
                _, _, r_ = self.exec_query(copy.deepcopy(s))
                new.extend(r_)
            if distinct:
                fresh = []
                for r in new:
                    k = tuple(datum_group_key(d, ft) for d, ft in zip(r, fts))
                    if k not in seen:
                        seen.add(k)
                        fresh.append(r)
                new = fresh
            total = total + new
            delta = new
        else:
            raise SubqueryError(
                f"recursive CTE {cte.name!r} exceeded cte_max_recursion_depth={self.max_recursion}"
            )
        self.registry.update_rows(cmeta, total)

    def rewrite_select(self, stmt: A.SelectStmt) -> None:
        """In-place: after this returns, `stmt` contains no subquery nodes
        (SemiJoinCond markers and materialized table references instead)."""
        stmt.from_clause = self._rewrite_from(stmt.from_clause)
        schema = self._from_schema(stmt.from_clause)
        # WHERE conjuncts get the full treatment (semi/anti markers allowed)
        conjs = [self._rewrite_conjunct(c, schema, stmt) for c in _split_conjuncts(stmt.where)]
        conjs = [c for c in conjs if c is not None]
        stmt.where = _and_all(conjs)
        # everywhere else only value-producing rewrites are legal
        for f in stmt.fields:
            if isinstance(f, A.SelectField):
                f.expr = self._rewrite_expr(f.expr, schema, stmt)
        if stmt.having is not None:
            stmt.having = self._rewrite_expr(stmt.having, schema, stmt)
        for b in list(stmt.group_by) + list(stmt.order_by):
            b.expr = self._rewrite_expr(b.expr, schema, stmt)

    # ------------------------------------------------------------- pieces
    def _view_of(self, name: str):
        """ViewMeta for a FROM reference, unless a CTE binding in any
        enclosing scope shadows it (MySQL: CTE names win inside the query,
        ref: logical_plan_builder.go buildDataSource CTE-before-view)."""
        n = name.lower()
        p = self
        while p is not None:
            if n in p.bindings:
                return None
            p = p.parent
        view_of = getattr(self.catalog, "view_of", None)
        return view_of(n) if view_of is not None else None

    def _expand_view(self, node: A.TableName):
        """TableName over a view -> SubqueryTable over its stored SELECT
        (re-parsed each use: the view sees the CURRENT schema, ref:
        ViewInfo expansion in buildDataSource)."""
        vm = self._view_of(node.name)
        if vm is None:
            return None
        depth = 0
        p = self
        while p is not None:
            depth += 1
            p = p.parent
        if depth > 24:
            raise SubqueryError(f"view nesting too deep expanding {node.name!r}")
        from ..parser import parse_one

        sel = parse_one(vm.select_sql)
        # the stored SELECT resolves against the view's DEFINING database
        # (derived from the catalog key prefix), not the session's current
        # one (ref: ViewInfo security/definer db in buildDataSource)
        from .session import qualify_tables_ast

        vdb = vm.name.rsplit(".", 1)[0] if "." in vm.name else "test"
        qualify_tables_ast(sel, vdb)
        if vm.columns:
            if not isinstance(sel, A.SelectStmt):
                raise SubqueryError("view column list over a UNION body is not supported yet")
            fields = sel.fields
            if any(isinstance(getattr(f, "expr", f), A.Star) for f in fields):
                raise SubqueryError("view column list with SELECT * is not supported yet")
            if len(fields) != len(vm.columns):
                raise SubqueryError(
                    f"view {vm.name!r}: column list arity {len(vm.columns)} != select list {len(fields)}"
                )
            for f, cn in zip(fields, vm.columns):
                f.alias = cn
        return A.SubqueryTable(sel, node.alias or node.name)

    def _rewrite_from(self, node):
        if isinstance(node, A.TableName):
            expanded = self._expand_view(node)
            if expanded is not None:
                node = expanded  # falls through to the SubqueryTable branch
            else:
                return node
        if node is None:
            return node
        if isinstance(node, A.SubqueryTable):
            names, fts, rows = self.exec_query(node.subquery)
            meta = self.registry.register(names, fts, rows)
            return A.TableName(meta.name, alias=node.alias)
        if isinstance(node, A.Join):
            node.left = self._rewrite_from(node.left)
            node.right = self._rewrite_from(node.right)
            return node
        return node

    def _is_correlated(self, sub, schema) -> bool:
        sels = sub.selects if isinstance(sub, A.SetOprStmt) else [sub]
        return any(
            self._refs_outer(sel, self._from_schema(sel.from_clause), [schema])
            for sel in sels
        )

    def _rewrite_conjunct(self, c, schema, stmt):
        """Top-level WHERE conjunct: IN/EXISTS may become join markers.
        Returns None to drop the conjunct (proven always-true)."""
        neg = False
        node = c
        while isinstance(node, A.UnaryOp) and node.op == "not" and isinstance(
            node.operand, (A.Exists, A.InSubquery)
        ):
            neg = not neg
            node = node.operand
        if isinstance(node, A.Exists):
            negated = node.negated ^ neg
            if not self._is_correlated(node.subquery, schema):
                return self._uncorrelated_exists(node.subquery, negated)
            try:
                return self._correlated_semi(node.subquery, schema, None, negated)
            except SubqueryError:
                return self._apply_fallback("exists", node.subquery, schema, stmt, negated=negated)
        if isinstance(node, A.InSubquery):
            negated = node.negated ^ neg
            if not self._is_correlated(node.subquery, schema):
                return self._uncorrelated_in(node, schema, stmt, negated)
            if not isinstance(node.expr, A.RowExpr):
                try:
                    x = self._rewrite_expr(copy.deepcopy(node.expr), schema, stmt)
                    return self._correlated_semi(node.subquery, schema, x, negated)
                except SubqueryError:
                    pass
            return self._apply_fallback(
                "in", node.subquery, schema, stmt,
                probe_exprs=_probe_items(node.expr), negated=negated,
            )
        return self._rewrite_expr(c, schema, stmt)

    def _rewrite_expr(self, n, schema, stmt):
        """Generic walk replacing value-position subqueries."""
        if not hasattr(n, "__dataclass_fields__"):
            return n
        if isinstance(n, A.SubqueryExpr):
            return self._scalar(n.subquery, schema, stmt)
        if isinstance(n, A.Exists):
            if self._is_correlated(n.subquery, schema):
                return self._apply_fallback("exists", n.subquery, schema, stmt, negated=n.negated)
            return self._uncorrelated_exists(n.subquery, n.negated)
        if isinstance(n, A.InSubquery):
            if self._is_correlated(n.subquery, schema):
                return self._apply_fallback(
                    "in", n.subquery, schema, stmt,
                    probe_exprs=_probe_items(n.expr), negated=n.negated,
                )
            return self._uncorrelated_in(n, schema, stmt, n.negated, conjunct=False)
        if isinstance(n, A.CompareSubquery):
            return self._compare_subquery(n, schema, stmt)
        for f_ in n.__dataclass_fields__:
            v = getattr(n, f_)
            if isinstance(v, list):
                for i, it in enumerate(v):
                    if isinstance(it, tuple):
                        v[i] = tuple(
                            self._rewrite_expr(x, schema, stmt) if isinstance(x, A.ExprNode) else x
                            for x in it
                        )
                    elif isinstance(it, A.ExprNode):
                        v[i] = self._rewrite_expr(it, schema, stmt)
            elif isinstance(v, A.ExprNode):
                setattr(n, f_, self._rewrite_expr(v, schema, stmt))
        return n

    # -------------------------------------------------- uncorrelated forms
    def _exec_values(self, sub):
        """Run an uncorrelated subquery; returns (fts, rows)."""
        names, fts, rows = self.exec_query(sub)
        return fts, rows

    def _uncorrelated_exists(self, sub, negated):
        limited = copy.deepcopy(sub)
        tgt = limited.selects[0] if isinstance(limited, A.SetOprStmt) else limited
        if tgt.limit is None and not isinstance(limited, A.SetOprStmt):
            tgt.limit = A.Limit(A.Literal(1, "int"))
        _, rows = self._exec_values(limited)
        exists = bool(rows)
        return TRUE_LIT() if exists ^ negated else FALSE_LIT()

    def _uncorrelated_in(self, node, schema, stmt, negated, conjunct=True):
        sub = node.subquery
        if isinstance(node.expr, A.RowExpr):
            return self._uncorrelated_tuple_in(node, schema, stmt, negated)
        fields = (sub.selects[0] if isinstance(sub, A.SetOprStmt) else sub).fields
        if len(fields) != 1 or isinstance(fields[0].expr if isinstance(fields[0], A.SelectField) else fields[0], A.Star):
            raise SubqueryError("IN subquery must select exactly one column")
        fts, rows = self._exec_values(sub)
        x = self._rewrite_expr(node.expr, schema, stmt)
        values = [r[0] for r in rows]
        # dedup (IN is a set membership test; collation-aware key)
        seen: set = set()
        uniq = []
        for d in values:
            k = datum_group_key(d, fts[0] if fts else None)
            if k not in seen:
                seen.add(k)
                uniq.append(d)
        if len(uniq) <= MAX_IN_LITERALS:
            if not uniq:
                # x IN () is never TRUE; x NOT IN () is always TRUE
                return None if (negated and conjunct) else (TRUE_LIT() if negated else FALSE_LIT())
            return A.InList(x, [_dlit(d) for d in uniq], negated=negated)
        if not conjunct:
            raise SubqueryError(
                f"IN subquery with >{MAX_IN_LITERALS} values is only supported as a WHERE conjunct"
            )
        has_null = any(d.is_null() for d in uniq)
        if negated and has_null:
            # x NOT IN (S ∪ {NULL}) is never TRUE (three-valued logic)
            return FALSE_LIT()
        nonnull = [d for d in uniq if not d.is_null()]
        meta = self.registry.register(["v"], [fts[0]], [[d] for d in nonnull])
        marker = A.SemiJoinCond(meta.name, [x], ["v"], anti=negated)
        if negated:
            # NULL probe against non-empty S is NULL -> row filtered; the
            # anti join alone would keep it
            return A.BinaryOp("and", marker, A.IsNull(copy.deepcopy(x), negated=True))
        return marker

    def _uncorrelated_tuple_in(self, node, schema, stmt, negated):
        """(a, b) [NOT] IN (select x, y ...): fold the materialized rows
        into OR-of-row-equalities — SQL's own AND/OR/= three-valued logic
        makes the NULL semantics exact (row comparison decomposes to
        component conjunction, ref: expression_rewriter.go buildRowExpr +
        the NAAJ semantics it feeds)."""
        fts, rows = self._exec_values(node.subquery)
        xs = [self._rewrite_expr(copy.deepcopy(p), schema, stmt) for p in node.expr.items]
        if rows and len(rows[0]) != len(xs):
            raise SubqueryError("IN row-value arity mismatch")
        if len(rows) > MAX_IN_LITERALS:
            raise SubqueryError(
                f"row-value IN subquery with >{MAX_IN_LITERALS} rows not supported"
            )
        if not rows:
            return TRUE_LIT() if negated else FALSE_LIT()
        disj = None
        for r in rows:
            eqs = [
                A.BinaryOp("eq", copy.deepcopy(x), _dlit(d))
                for x, d in zip(xs, r)
            ]
            conj = eqs[0]
            for e in eqs[1:]:
                conj = A.BinaryOp("and", conj, e)
            disj = conj if disj is None else A.BinaryOp("or", disj, conj)
        return A.UnaryOp("not", disj) if negated else disj

    def _compare_subquery(self, n: A.CompareSubquery, schema, stmt):
        """cmp ANY/ALL folding over the materialized value set
        (ref: expression_rewriter.go handleCompareSubquery min/max rewrite)."""
        if self._is_correlated(n.subquery, schema):
            return self._apply_fallback(
                "cmp", n.subquery, schema, stmt,
                probe_exprs=[n.expr], cmp_op=n.op, cmp_all=n.all,
            )
        if isinstance(n.expr, A.RowExpr) and (
            (n.op == "eq" and not n.all) or (n.op == "ne" and n.all)
        ):
            # (a,b) = ANY (...) == row IN; (a,b) != ALL (...) == row NOT IN
            # (ref: expression_rewriter.go handleCompareSubquery NAAJ path)
            shim = A.InSubquery(n.expr, n.subquery, negated=(n.op == "ne"))
            return self._uncorrelated_tuple_in(shim, schema, stmt, n.op == "ne")
        fts, rows = self._exec_values(n.subquery)
        x = self._rewrite_expr(n.expr, schema, stmt)
        values = [r[0] for r in rows]
        has_null = any(d.is_null() for d in values)
        nonnull = [d for d in values if not d.is_null()]
        if n.op == "eq" and not n.all:  # = ANY == IN
            return self._fold_in(x, values, negated=False)
        if n.op == "ne" and n.all:  # <> ALL == NOT IN
            return self._fold_in(x, values, negated=True)
        if not values:
            return TRUE_LIT() if n.all else FALSE_LIT()
        if not nonnull:
            return NULL_LIT()
        mn = min(nonnull, key=lambda d: _cmp_key(d, nonnull[0]))
        mx = max(nonnull, key=lambda d: _cmp_key(d, nonnull[0]))
        if n.op in ("lt", "le", "gt", "ge"):
            bound = {
                ("lt", True): mn, ("le", True): mn, ("gt", True): mx, ("ge", True): mx,
                ("lt", False): mx, ("le", False): mx, ("gt", False): mn, ("ge", False): mn,
            }[(n.op, n.all)]
            cond = A.BinaryOp(n.op, x, _dlit(bound))
            if has_null:
                # AND NULL: TRUE->NULL, FALSE->FALSE (ALL); OR NULL:
                # TRUE->TRUE, FALSE->NULL (ANY) — exact three-valued fold
                cond = A.BinaryOp("and" if n.all else "or", cond, NULL_LIT())
            return cond
        if n.op == "eq" and n.all:
            # x = ALL(S): all values equal x
            cond = A.BinaryOp("and", A.BinaryOp("eq", x, _dlit(mn)), A.BinaryOp("eq", copy.deepcopy(x), _dlit(mx)))
            if has_null:
                cond = A.BinaryOp("and", cond, NULL_LIT())
            return cond
        if n.op == "ne" and not n.all:
            # x <> ANY(S): some value differs from x
            cond = A.BinaryOp("or", A.BinaryOp("ne", x, _dlit(mn)), A.BinaryOp("ne", copy.deepcopy(x), _dlit(mx)))
            if has_null:
                cond = A.BinaryOp("or", cond, NULL_LIT())
            return cond
        raise SubqueryError(f"comparison {n.op!r} ANY/ALL not supported")

    def _fold_in(self, x, values, negated):
        seen: set = set()
        uniq = []
        for d in values:
            k = datum_group_key(d)
            if k not in seen:
                seen.add(k)
                uniq.append(d)
        if not uniq:
            return TRUE_LIT() if negated else FALSE_LIT()
        if len(uniq) > MAX_IN_LITERALS:
            raise SubqueryError("ANY/ALL over large value sets not supported in value position")
        return A.InList(x, [_dlit(d) for d in uniq], negated=negated)

    # --------------------------------------------------- correlated forms
    # ----------------------------------------------------- apply fallback
    def _walk_outer_cols(self, node, schema, visit):
        """Walk `node` (a subquery AST) visiting every ColumnName that
        resolves ONLY in the enclosing `schema` (not in its local scope
        chain). `visit(parent, field, index_or_None, colname)` may return a
        replacement node. Mirrors _refs_outer's scope-stack walk."""

        def outer_only(n, schemas) -> bool:
            return (
                isinstance(n, A.ColumnName)
                and not any(self._resolves(n, s) for s in schemas[1:])
                and self._resolves(n, schemas[0])
            )

        def maybe(parent, f_, i, n, schemas):
            if isinstance(n, A.ColumnName):
                if outer_only(n, schemas):
                    rep = visit(n)
                    if rep is not None:
                        if i is None:
                            setattr(parent, f_, rep)
                        else:
                            getattr(parent, f_)[i] = rep
                return
            walk(n, schemas)

        def walk(n, schemas):
            if not hasattr(n, "__dataclass_fields__"):
                return
            sub = getattr(n, "subquery", None)
            if sub is not None and not isinstance(n, A.SubqueryTable):
                for sel in (sub.selects if isinstance(sub, A.SetOprStmt) else [sub]):
                    walk_stmt(sel, schemas + [self._from_schema(sel.from_clause)])
            for f_ in n.__dataclass_fields__:
                if f_ == "subquery":
                    continue
                v = getattr(n, f_)
                if isinstance(v, list):
                    for i, it in enumerate(v):
                        if isinstance(it, tuple):
                            # tuple elements (CASE when/then pairs) may BE
                            # bare outer columns: rebuild the tuple
                            newt, changed = [], False
                            for x in it:
                                if outer_only(x, schemas):
                                    rep = visit(x)
                                    if rep is not None:
                                        x, changed = rep, True
                                else:
                                    walk(x, schemas)
                                newt.append(x)
                            if changed:
                                v[i] = tuple(newt)
                        elif hasattr(it, "__dataclass_fields__"):
                            maybe(n, f_, i, it, schemas)
                elif hasattr(v, "__dataclass_fields__"):
                    maybe(n, f_, None, v, schemas)

        def walk_stmt(sel, schemas):
            if isinstance(sel, A.SetOprStmt):
                for s in sel.selects:
                    walk_stmt(s, schemas)
                return
            for f in sel.fields:
                walk(f, schemas)
            for f_ in ("where", "having"):
                part = getattr(sel, f_)
                if part is not None:
                    maybe(sel, f_, None, part, schemas)
            for b in list(sel.group_by) + list(sel.order_by):
                maybe(b, "expr", None, b.expr, schemas)

            def walk_from(fr):
                if isinstance(fr, A.Join):
                    walk_from(fr.left)
                    walk_from(fr.right)
                    if fr.on is not None:
                        walk(fr.on, schemas)
            walk_from(sel.from_clause)

        sels = node.selects if isinstance(node, A.SetOprStmt) else [node]
        for sel in sels:
            walk_stmt(sel, [schema, self._from_schema(sel.from_clause)])

    def _apply_fallback(self, kind, sub, schema, stmt, probe_exprs=(), negated=False, cmp_op=None, cmp_all=False):
        """Correlated subquery the decorrelator can't handle -> register a
        host-evaluated function that re-executes the inner per outer row
        (deduplicated by binding), and rewrite to a call on the outer refs.
        kind: exists | in | scalar | cmp."""
        from ..exec.executor import datum_group_key as _gk
        from ..types import new_longlong
        from .extension import EXTENSIONS
        from .planner import datum_ft

        refs: list = []
        ref_keys: dict = {}

        def collect(c: A.ColumnName):
            k = (c.db.lower(), c.table.lower(), c.name.lower())
            if k not in ref_keys:
                ref_keys[k] = len(refs)
                refs.append(A.ColumnName(c.name, c.table, c.db))
            return None

        self._walk_outer_cols(sub, schema, collect)
        if not refs:
            raise SubqueryError("correlated subquery has no resolvable outer references")
        probes = [self._rewrite_expr(copy.deepcopy(p), schema, stmt) for p in probe_exprs]
        np_ = len(probes)
        cache: dict = {}
        exec_query = self.exec_query
        resolves = self._resolves
        from_schema = self._from_schema
        walker = self._walk_outer_cols

        def tuple_in_3vl(xs, rows):
            if rows and len(rows[0]) != len(xs):
                from .session import SQLError

                raise SQLError(f"Operand should contain {len(xs)} column(s)")
            any_unknown = False
            for r in rows:
                all_true, unknown = True, False
                for x, s in zip(xs, r):
                    if x.is_null() or s.is_null():
                        unknown = True
                        continue
                    if compare(x, s) != 0:
                        all_true = False
                        unknown = False
                        break
                if all_true and not unknown:
                    return Datum.i64(0) if negated else Datum.i64(1)
                if unknown:
                    any_unknown = True
            if any_unknown:
                return Datum.NULL
            return Datum.i64(1) if negated else Datum.i64(0)

        def run(datums):
            key = tuple(_gk(d) for d in datums)
            if key in cache:
                return cache[key]
            bind = datums[np_:]
            sub2 = copy.deepcopy(sub)

            def subst(c: A.ColumnName):
                i = ref_keys.get((c.db.lower(), c.table.lower(), c.name.lower()))
                return _dlit(bind[i]) if i is not None else None

            walker(sub2, schema, subst)
            names, fts, rows = exec_query(sub2)
            if kind == "exists":
                out = Datum.i64(1 if bool(rows) ^ negated else 0)
            elif kind == "in":
                out = tuple_in_3vl(datums[:np_], rows)
            elif kind == "scalar":
                if len(rows) > 1:
                    # runtime (not rewrite-time) error: surface as SQLError
                    # so the session reports it like any statement error
                    from .session import SQLError

                    raise SQLError("Subquery returns more than 1 row")
                out = rows[0][0] if rows else Datum.NULL
            else:  # cmp ANY/ALL
                x = datums[0]
                vals = [r[0] for r in rows]
                if not vals:
                    out = Datum.i64(1 if cmp_all else 0)
                elif x.is_null():
                    out = Datum.NULL
                else:
                    import operator

                    opf = {"lt": operator.lt, "le": operator.le, "gt": operator.gt,
                           "ge": operator.ge, "eq": operator.eq, "ne": operator.ne}[cmp_op]
                    res, unknown = (True if cmp_all else False), False
                    for v in vals:
                        if v.is_null():
                            unknown = True
                            continue
                        ok = opf(compare(x, v), 0)
                        if cmp_all and not ok:
                            res = False
                            unknown = False
                            break
                        if not cmp_all and ok:
                            res = True
                            unknown = False
                            break
                    out = Datum.NULL if unknown else Datum.i64(1 if res else 0)
            cache[key] = out
            return out

        fname = f"__apply_{id(sub):x}_{len(EXTENSIONS.functions)}"
        if kind == "scalar":
            # discover the result type from one NULL-bound probe run; on
            # any failure surface the original unsupported-shape error
            try:
                sub_t = copy.deepcopy(sub)
                walker(sub_t, schema, lambda c: A.Literal(None, "null"))
                _, t_fts, _ = exec_query(sub_t)
                ft = t_fts[0] if t_fts else new_longlong()
            except Exception as exc:  # noqa: BLE001
                raise SubqueryError(f"correlated scalar subquery not supported: {exc}") from exc
        else:
            ft = new_longlong()
        EXTENSIONS.register_function(fname, run, ft, raw=True)
        return A.FuncCall(fname, probes + refs)

    def _extract_corr(self, sub: A.SelectStmt, schema):
        """Split the inner WHERE into local conjuncts and correlation pairs
        (inner_expr, outer_expr). Raises unless every correlated conjunct
        is an equality with one pure-inner and one pure-outer side."""
        if isinstance(sub, A.SetOprStmt):
            raise SubqueryError("correlated UNION subqueries not supported")
        if sub.limit is not None or sub.order_by:
            raise SubqueryError("correlated subqueries with ORDER BY/LIMIT not supported")
        if sub.having is not None:
            raise SubqueryError("correlated subqueries with HAVING not supported")
        inner_schema = self._from_schema(sub.from_clause)
        local, pairs = [], []
        for c in _split_conjuncts(sub.where):
            if not self._refs_outer(c, inner_schema, [schema]):
                local.append(c)
                continue
            if not (isinstance(c, A.BinaryOp) and c.op == "eq"):
                raise SubqueryError(
                    "correlated subqueries support equality correlation only "
                    f"(got {type(c).__name__})"
                )

            def side_kind(e):
                refs_i = [False]
                refs_o = [False]

                def walk(x):
                    if isinstance(x, A.ColumnName):
                        if self._resolves(x, inner_schema):
                            refs_i[0] = True
                        elif self._resolves(x, schema):
                            refs_o[0] = True
                        return
                    if hasattr(x, "__dataclass_fields__"):
                        for f_ in x.__dataclass_fields__:
                            v = getattr(x, f_)
                            for it in v if isinstance(v, (list, tuple)) else [v]:
                                if hasattr(it, "__dataclass_fields__"):
                                    walk(it)

                walk(e)
                if refs_i[0] and refs_o[0]:
                    return "mixed"
                return "outer" if refs_o[0] else "inner"

            lk, rk = side_kind(c.left), side_kind(c.right)
            if lk == "inner" and rk == "outer":
                pairs.append((c.left, c.right))
            elif lk == "outer" and rk == "inner":
                pairs.append((c.right, c.left))
            else:
                raise SubqueryError(
                    "correlated equality must have one inner-only and one outer-only side"
                )
        if not pairs:
            raise SubqueryError("correlated subquery has no usable equality correlation")
        return local, pairs

    def _correlated_semi(self, sub, schema, in_expr, negated):
        """Correlated [NOT] IN / [NOT] EXISTS conjunct -> SemiJoinCond."""
        if isinstance(sub, A.SetOprStmt):
            raise SubqueryError("correlated UNION subqueries not supported")
        if sub.group_by or any(_has_agg_field(f) for f in sub.fields):
            raise SubqueryError("correlated IN/EXISTS with aggregation not supported")
        local, pairs = self._extract_corr(sub, schema)
        fields = []
        if in_expr is not None:
            inner_fields = sub.fields
            if len(inner_fields) != 1:
                raise SubqueryError("IN subquery must select exactly one column")
            ve = inner_fields[0].expr if isinstance(inner_fields[0], A.SelectField) else inner_fields[0]
            if isinstance(ve, A.Star):
                raise SubqueryError("IN subquery must select exactly one column")
            fields.append(A.SelectField(ve, "v"))
        for i, (ie, _) in enumerate(pairs):
            fields.append(A.SelectField(ie, f"k{i}"))
        mat_sel = A.SelectStmt(fields=fields, from_clause=sub.from_clause, where=_and_all(local))
        names, fts, rows = self.exec_query(mat_sel)
        probe = ([in_expr] if in_expr is not None else []) + [oe for _, oe in pairs]
        build = list(names)
        if in_expr is not None and negated:
            # rows whose value is NULL poison their whole correlation group
            # (x NOT IN {... NULL} is never TRUE): a second anti join on the
            # correlation keys alone removes probes of poisoned groups
            null_rows = [r[1:] for r in rows if r[0].is_null()]
            rows = [r for r in rows if not r[0].is_null()]
            meta = self.registry.register(build, fts, rows)
            marker = A.SemiJoinCond(meta.name, probe, build, anti=True, require_notnull_probe=True)
            if null_rows and pairs:
                nmeta = self.registry.register(build[1:], fts[1:], null_rows)
                poison = A.SemiJoinCond(nmeta.name, [copy.deepcopy(oe) for _, oe in pairs], build[1:], anti=True)
                return A.BinaryOp("and", marker, poison)
            if null_rows and not pairs:
                return FALSE_LIT()
            return marker
        meta = self.registry.register(build, fts, rows)
        return A.SemiJoinCond(meta.name, probe, build, anti=negated)

    def _scalar(self, sub, schema, stmt):
        """Scalar subquery in value position."""
        if isinstance(sub, A.SetOprStmt):
            sel = sub.selects[0]
        else:
            sel = sub
        n_fields = len(sel.fields)
        if n_fields != 1:
            raise SubqueryError("scalar subquery must select exactly one column")
        if not self._is_correlated(sub, schema):
            _, rows = self._exec_values(sub)
            if len(rows) > 1:
                raise SubqueryError("Subquery returns more than 1 row")
            return _dlit(rows[0][0]) if rows else NULL_LIT()
        if isinstance(sub, A.SetOprStmt):
            return self._apply_fallback("scalar", sub, schema, stmt)
        try:
            return self._scalar_corr(copy.deepcopy(sub), schema, stmt)
        except SubqueryError:
            return self._apply_fallback("scalar", sub, schema, stmt)

    def _scalar_corr(self, sub: A.SelectStmt, schema, stmt):
        """Correlated scalar subquery -> LEFT JOIN against the inner
        re-grouped by its correlation keys (ref: rule_decorrelate.go's
        aggregate pull-up producing an outer join)."""
        if sub.group_by:
            raise SubqueryError("correlated scalar subqueries with GROUP BY not supported")
        local, pairs = self._extract_corr(sub, schema)
        f0 = sub.fields[0]
        ve = f0.expr if isinstance(f0, A.SelectField) else f0
        if isinstance(ve, A.Star):
            raise SubqueryError("scalar subquery must select exactly one column")
        inner_schema = self._from_schema(sub.from_clause)
        if self._refs_outer(ve, inner_schema, [schema]):
            raise SubqueryError("outer references in a scalar subquery's select list not supported")
        has_agg = _has_agg_expr(ve)
        fields = [A.SelectField(ie, f"k{i}") for i, (ie, _) in enumerate(pairs)]
        fields.append(A.SelectField(ve, "v"))
        mat_sel = A.SelectStmt(fields=fields, from_clause=sub.from_clause, where=_and_all(local))
        if has_agg:
            mat_sel.group_by = [A.ByItem(copy.deepcopy(ie)) for ie, _ in pairs]
        names, fts, rows = self.exec_query(mat_sel)
        if not has_agg:
            keys = set()
            for r in rows:
                k = tuple(datum_group_key(d, ft) for d, ft in zip(r[:-1], fts))
                if k in keys:
                    raise SubqueryError("Subquery returns more than 1 row")
                keys.add(k)
        meta = self.registry.register(names, fts, rows)
        alias = "_sq_" + meta.name.lstrip("#")
        on = _and_all([
            A.BinaryOp("eq", copy.deepcopy(oe), A.ColumnName(f"k{i}", alias))
            for i, (_, oe) in enumerate(pairs)
        ])
        stmt.from_clause = A.Join(stmt.from_clause, A.TableName(meta.name, alias=alias), "left", on)
        ref = A.ColumnName("v", alias)
        if isinstance(ve, A.AggFunc) and ve.name.lower() == "count":
            # COUNT over an empty correlation group is 0, not NULL — the
            # left join's null extension must be patched back
            return A.FuncCall("ifnull", [ref, A.Literal(0, "int")])
        return ref


def _has_agg_expr(n) -> bool:
    if isinstance(n, A.AggFunc):
        return True
    if not hasattr(n, "__dataclass_fields__"):
        return False
    for f_ in n.__dataclass_fields__:
        v = getattr(n, f_)
        for it in v if isinstance(v, (list, tuple)) else [v]:
            if isinstance(it, tuple):
                if any(_has_agg_expr(x) for x in it):
                    return True
            elif _has_agg_expr(it):
                return True
    return False


def _has_agg_field(f) -> bool:
    return _has_agg_expr(f.expr if isinstance(f, A.SelectField) else f)


class _CmpWrap:
    """Total-order wrapper for min/max over homogeneous datums."""

    __slots__ = ("d",)

    def __init__(self, d):
        self.d = d

    def __lt__(self, other):
        return compare(self.d, other.d) < 0

    def __eq__(self, other):
        return compare(self.d, other.d) == 0


def _cmp_key(d: Datum, ref: Datum) -> _CmpWrap:
    return _CmpWrap(d)
