"""SHOW CREATE TABLE rendering in the reference's exact output shape
(ref: pkg/executor/show.go fetchShowCreateTable / ConstructResultOfShowCreateTable).

The engine normalizes storage types (every int width becomes an int64
lane, every string a packed varchar), so ColumnMeta carries the declared
spelling (`decl`) and this module only has to re-assemble the DDL text:
column lines, generated-column clauses (a minimal AST unparser — the
reference keeps GeneratedExprString verbatim), the clustered PRIMARY KEY
comment, and the InnoDB/charset footer the integration results expect."""

from __future__ import annotations

from ..parser import ast as A

_BINOP_SQL = {
    "plus": "+", "minus": "-", "mul": "*", "div": "/", "intdiv": "DIV",
    "mod": "%", "eq": "=", "ne": "!=", "lt": "<", "le": "<=", "gt": ">",
    "ge": ">=", "nulleq": "<=>", "and": "and", "or": "or", "xor": "xor",
    "bitand": "&", "bitor": "|", "bitxor": "^", "shiftleft": "<<",
    "shiftright": ">>",
}


def expr_sql(e) -> str:
    """Minimal AST -> SQL text (generated columns, CHECK, defaults)."""
    if isinstance(e, A.Literal):
        if e.kind == "null" or e.value is None:
            return "NULL"
        if e.kind in ("str",):
            v = e.value if isinstance(e.value, str) else e.value.decode("utf-8", "replace")
            return "'" + v.replace("'", "''") + "'"
        if e.kind == "bool":
            return "TRUE" if e.value else "FALSE"
        return str(e.value)
    if isinstance(e, A.ColumnName):
        return f"`{e.name}`"
    if isinstance(e, A.BinaryOp):
        return f"{expr_sql(e.left)} {_BINOP_SQL.get(e.op, e.op)} {expr_sql(e.right)}"
    if isinstance(e, A.UnaryOp):
        op = {"not": "not ", "unaryminus": "-", "bitneg": "~"}.get(e.op, e.op)
        return f"{op}{expr_sql(e.operand)}"
    if isinstance(e, A.FuncCall):
        return f"{e.name}({', '.join(expr_sql(a) for a in e.args)})"
    if isinstance(e, A.IsNull):
        return f"{expr_sql(e.expr)} is {'not ' if e.negated else ''}null"
    if isinstance(e, A.Between):
        neg = "not " if e.negated else ""
        return f"{expr_sql(e.expr)} {neg}between {expr_sql(e.low)} and {expr_sql(e.high)}"
    if isinstance(e, A.InList):
        neg = "not " if e.negated else ""
        return f"{expr_sql(e.expr)} {neg}in ({', '.join(expr_sql(a) for a in e.items)})"
    if isinstance(e, A.Case):
        parts = ["case"]
        if e.operand is not None:
            parts.append(expr_sql(e.operand))
        for w, t in e.when_clauses:
            parts.append(f"when {expr_sql(w)} then {expr_sql(t)}")
        if e.else_clause is not None:
            parts.append(f"else {expr_sql(e.else_clause)}")
        parts.append("end")
        return " ".join(parts)
    if isinstance(e, A.Cast):
        ts = e.to_type
        from .catalog import decl_text

        return f"cast({expr_sql(e.expr)} as {decl_text(ts)})"
    if isinstance(e, A.Like):
        neg = "not " if e.negated else ""
        return f"{expr_sql(e.expr)} {neg}like {expr_sql(e.pattern)}"
    return str(e)


def _fallback_decl(ft) -> str:
    et = ft.eval_type()
    if et == "int":
        return "bigint unsigned" if ft.is_unsigned() else "bigint"
    if et == "real":
        return "double"
    if et == "decimal":
        return f"decimal({ft.flen},{max(ft.decimal, 0)})"
    if et == "time":
        return "datetime"
    if et == "json":
        return "json"
    return f"varchar({ft.flen})" if ft.flen > 0 else "text"


def _default_sql(cm) -> str:
    d = cm.default
    if isinstance(d, A.FuncCall) and d.name in ("current_timestamp", "now"):
        return "CURRENT_TIMESTAMP"
    if isinstance(d, A.Literal):
        return expr_sql(d)
    return f"({expr_sql(d)})"


def show_create_table(meta) -> str:
    short = meta.name.rsplit(".", 1)[-1]  # strip any database prefix
    lines = [f"CREATE TABLE `{short}` ("]
    body = []
    from ..types import Flag

    for cm in meta.columns:
        decl = cm.decl or _fallback_decl(cm.ft)
        parts = [f"`{cm.name}`", decl]
        if cm.generated is not None:
            parts.append(f"GENERATED ALWAYS AS ({expr_sql(cm.generated)})")
            parts.append("STORED" if cm.generated_stored else "VIRTUAL")
        notnull = bool(cm.ft.flag & Flag.NotNull) or cm.name == meta.handle_col
        if notnull:
            parts.append("NOT NULL")
        if cm.auto_increment:
            parts.append("AUTO_INCREMENT")
        elif cm.default is not None and cm.generated is None:
            parts.append(f"DEFAULT {_default_sql(cm)}")
        elif not notnull and cm.generated is None:
            parts.append("DEFAULT NULL")
        body.append("  " + " ".join(parts))
    if meta.handle_col is not None:
        body.append(f"  PRIMARY KEY (`{meta.handle_col}`) /*T![clustered_index] CLUSTERED */")
    for idx in meta.indices:
        if idx.state != "public":
            continue
        cols = ",".join(f"`{c}`" for c in idx.col_names)
        if idx.name == "PRIMARY":
            body.append(f"  PRIMARY KEY ({cols}) /*T![clustered_index] NONCLUSTERED */")
            continue
        kind = "UNIQUE KEY" if idx.unique else "KEY"
        body.append(f"  {kind} `{idx.name}` ({cols})")
    for fk in getattr(meta, "foreign_keys", []):
        cols = ",".join(f"`{c}`" for c in fk.cols)
        rcols = ",".join(f"`{c}`" for c in fk.ref_cols)
        rt = fk.ref_table.rsplit(".", 1)[-1]
        line = f"  CONSTRAINT `{fk.name}` FOREIGN KEY ({cols}) REFERENCES `{rt}` ({rcols})"
        if fk.on_delete != "restrict":
            line += f" ON DELETE {fk.on_delete.replace('_', ' ').upper()}"
        if fk.on_update != "restrict":
            line += f" ON UPDATE {fk.on_update.replace('_', ' ').upper()}"
        body.append(line)
    out = lines[0] + "\n" + ",\n".join(body) + "\n"
    out += ") ENGINE=InnoDB DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin"
    return out
