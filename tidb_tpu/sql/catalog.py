"""Catalog: table metadata keyed by name — the dict-backed infoschema/meta
analog (ref: pkg/infoschema InfoSchema, pkg/meta/model TableInfo/ColumnInfo;
schema versioning and the domain reload loop collapse to a monotonic version
counter in one process).

CREATE TABLE feeds this from the parsed AST; the planner resolves names
through it; the session allocates row handles from its per-table autoid
(ref: pkg/meta/autoid)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

# handle/col-id allocations are tiny critical sections; one module lock
# keeps TableMeta a plain dataclass (ref: meta/autoid's own mutex)
_ALLOC_LOCK = threading.Lock()

from ..parser import ast as A
from ..types import Collation, FieldType, Flag, TypeCode, new_datetime, new_decimal, new_double, new_longlong, new_varchar


class CatalogError(ValueError):
    pass


@dataclass
class FKMeta:
    """(ref: pkg/meta/model FKInfo)."""

    name: str
    cols: list  # child column names
    ref_table: str  # catalog key of the parent
    ref_cols: list
    on_delete: str = "restrict"
    on_update: str = "restrict"


def decl_text(ts: A.TypeSpec) -> str:
    """Declared type spelling for SHOW CREATE TABLE (ref: the reference
    round-trips meta/model FieldType through types.StrFor SHOW; here the
    storage types are normalized so the spelling must be kept)."""
    name = ts.name
    out = name
    if ts.length > 0 and ts.decimal >= 0 and name == "decimal":
        out = f"decimal({ts.length},{ts.decimal})"
    elif name == "decimal":
        out = "decimal(10,0)"
    elif ts.length > 0 and name in ("char", "varchar", "binary", "varbinary", "bit"):
        out = f"{name}({ts.length})"
    elif ts.decimal > 0 and name in ("datetime", "timestamp", "time"):
        out = f"{name}({ts.decimal})"
    elif ts.elems:
        vals = ",".join("'" + e.replace("'", "''") + "'" for e in ts.elems)
        out = f"{name}({vals})"
    if ts.unsigned:
        out += " unsigned"
    if ts.zerofill:
        out += " zerofill"
    return out


def field_type_from_spec(ts: A.TypeSpec, not_null: bool = False) -> FieldType:
    """TypeSpec (DDL/CAST AST) -> FieldType (ref: pkg/parser/types -> tipb
    ColumnInfo mapping in pkg/tablecodec)."""
    name = ts.name
    if name in ("tinyint", "smallint", "mediumint", "int", "bigint", "year", "bit"):
        ft = new_longlong(unsigned=ts.unsigned or name == "bit", notnull=not_null)
        return ft
    if name in ("float", "double"):
        return FieldType(TypeCode.Double, flag=Flag.NotNull if not_null else Flag(0))
    if name == "decimal":
        prec = ts.length if ts.length > 0 else 10
        scale = ts.decimal if ts.decimal >= 0 else 0
        ft = new_decimal(prec, scale)
        if not_null:
            ft = FieldType(ft.tp, ft.flag | Flag.NotNull, ft.flen, ft.decimal)
        return ft
    if name == "json":
        from ..types import new_json

        ft = new_json()
        if not_null:
            ft = FieldType(ft.tp, ft.flag | Flag.NotNull, ft.flen, ft.decimal)
        return ft
    if name in ("enum", "set"):
        from ..types import new_enum, new_set

        mk = new_enum if name == "enum" else new_set
        return mk(tuple(ts.elems), notnull=not_null)
    if name in ("char", "varchar", "binary", "varbinary", "text", "tinytext", "mediumtext", "longtext",
                "blob", "tinyblob", "mediumblob", "longblob"):
        flen = ts.length if ts.length > 0 else (1 if name == "binary" else 255)
        ft = new_varchar(flen)
        # byte-semantics functions (LENGTH/HEX/ASCII) consult the declared
        # charset (ref: types.FieldType.GetCharset feeding builtin_string);
        # binary types carry "binary" + the BINARY(n) zero-pad width
        if name in ("binary", "varbinary", "blob", "tinyblob", "mediumblob", "longblob"):
            ft.charset = "binary"
            if name == "binary":
                # fixed BINARY(n): TypeCode.String marks the zero-pad width
                # contract (planner._coerce_datum pads on write; ref:
                # pkg/table/column.go ProduceStrWithSpecifiedTp)
                ft.tp = TypeCode.String
        elif ts.charset:
            ft.charset = ts.charset.lower()
        if ts.collate:
            c = ts.collate.lower()
            if c.endswith("_general_ci"):
                ft.collate = Collation.Utf8MB4GeneralCI
            elif c.endswith(("_unicode_ci", "_0900_ai_ci", "_unicode_520_ci")):
                ft.collate = Collation.Utf8MB4UnicodeCI
            elif c.endswith("_bin") or c == "binary":
                ft.collate = Collation.Utf8MB4Bin
        if not_null:
            ft = FieldType(ft.tp, ft.flag | Flag.NotNull, ft.flen, ft.decimal, ft.charset, ft.collate)
        return ft
    if name in ("date", "datetime", "timestamp"):
        fsp = ts.decimal if ts.decimal > 0 else 0
        ft = new_datetime(fsp)
        if not_null:
            ft = FieldType(ft.tp, ft.flag | Flag.NotNull, ft.flen, ft.decimal)
        return ft
    if name == "time":  # duration stored as int64 nanoseconds
        return new_longlong(notnull=not_null)
    raise CatalogError(f"unsupported column type {name!r}")


@dataclass
class ColumnMeta:
    name: str
    col_id: int
    ft: FieldType
    default: object = None  # parsed AST default, evaluated at insert
    auto_increment: bool = False
    origin_default: object = None  # Datum filled for rows older than an
    # ADD COLUMN (ref: meta/model ColumnInfo.OriginDefaultValue)
    generated: object = None  # GENERATED ALWAYS AS expr AST (ref:
    # meta/model ColumnInfo.GeneratedExprString; executor computes at
    # write, pkg/table/column.go CastValue + BuildRowcodecColInfo)
    generated_stored: bool = False
    decl: str | None = None  # declared SQL type text ("int", "char(20)")
    # — the engine normalizes storage types (all ints -> int64 lanes), so
    # SHOW CREATE TABLE needs the original spelling preserved


@dataclass
class IndexMeta:
    """(ref: meta/model IndexInfo). `state` walks the F1 online-schema
    states during ADD INDEX (ddl.py): delete_only -> write_only ->
    write_reorg -> public. Readers use public indexes only; DML writes
    entries from write_only on and honors deletes in every state."""

    name: str
    index_id: int
    col_names: list
    unique: bool = False
    state: str = "public"


@dataclass
class TableMeta:
    name: str
    table_id: int
    columns: list  # [ColumnMeta]
    indices: list = field(default_factory=list)  # [IndexMeta]
    handle_col: str | None = None  # integer PRIMARY KEY column used as row handle
    _next_handle: int = 1  # autoid cursor (ref: meta/autoid); guarded_by: _ALLOC_LOCK
    row_count: int = 0  # maintained by DML; the planner's only "statistic"
    next_col_id: int = 0  # max-ever col id + 1: DROP COLUMN must never free
    # its id for reuse (old rows still hold bytes under it)
    partition: "PartitionInfo | None" = None  # RANGE/HASH partitioning
    foreign_keys: list = field(default_factory=list)  # [FKMeta] (ref:
    # meta/model FKInfo; checked at DML by executor/foreign_key.go analog)
    # per-table ROW-SHAPE version: bumped by column DDL (add/drop/modify/
    # rename) but not by index or placement changes. Changefeeds stamp it
    # at birth and park on drift instead of silently mounting old rows
    # against a new catalog (ISSUE 12 satellite; ref: TiCDC's
    # schema-tracker snapshot keyed by schema version)
    schema_version: int = 0

    def __post_init__(self):
        if self.next_col_id <= 0:
            self.next_col_id = max((c.col_id for c in self.columns), default=0) + 1

    def col(self, name: str) -> ColumnMeta:
        for c in self.columns:
            if c.name == name.lower():
                return c
        raise CatalogError(f"unknown column {name!r} in table {self.name!r}")

    def scan_columns(self) -> tuple:
        """ColumnInfos for a full-row scan of this table."""
        from ..exec.dag import ColumnInfo

        return tuple(ColumnInfo(c.col_id, c.ft, c.origin_default) for c in self.columns)

    def col_ids(self) -> list:
        return [c.col_id for c in self.columns]

    def physical_ids(self) -> list:
        """Key-space ids rows live under: per-partition pids, or the table
        id itself (ref: PartitionDefinition.ID vs TableInfo.ID)."""
        if self.partition is not None:
            return [p.pid for p in self.partition.parts]
        return [self.table_id]

    def pid_for_row(self, datums: list) -> int:
        """Physical id the row belongs to (partition routing by the
        partition column's value; unpartitioned -> table_id)."""
        if self.partition is None:
            return self.table_id
        i = next(j for j, c in enumerate(self.columns) if c.name == self.partition.col)
        d = datums[i]
        return self.partition.route(None if d.is_null() else int(d.val))

    def fts(self) -> list:
        return [c.ft for c in self.columns]

    def alloc_handle(self) -> int:
        with _ALLOC_LOCK:
            h = self._next_handle
            self._next_handle += 1
            return h

    def peek_handle(self) -> int:
        with _ALLOC_LOCK:
            return self._next_handle

    def observe_handle(self, h: int):
        """Explicit-PK inserts advance the allocator past the used value
        (MySQL auto_increment semantics; ref: meta/autoid rebase)."""
        with _ALLOC_LOCK:
            if h >= self._next_handle:
                self._next_handle = h + 1

    def alloc_col_id(self) -> int:
        with _ALLOC_LOCK:
            v = self.next_col_id
            self.next_col_id += 1
            return v


@dataclass
class PartitionDef:
    """One physical partition: its own key space under `pid`
    (ref: meta/model PartitionDefinition — per-partition physical IDs)."""

    name: str
    pid: int
    upper: int | None = None  # RANGE: exclusive upper bound; None = MAXVALUE


@dataclass
class PartitionInfo:
    """RANGE/HASH partitioning over one integer column (ref: meta/model
    PartitionInfo; pruning rule_partition_processor.go). Each partition is
    a separate physical key space; the logical table routes rows by the
    partition column's value."""

    method: str  # "range" | "hash"
    col: str
    parts: list  # [PartitionDef]

    def route(self, val) -> int:
        """Partition id for a column value (None = NULL).

        NULL routes to the FIRST partition (MySQL: NULL is less than any
        non-NULL for RANGE; hashes as 0 for HASH)."""
        if self.method == "hash":
            if val is None:
                return self.parts[0].pid
            return self.parts[int(val) % len(self.parts)].pid
        if val is None:
            return self.parts[0].pid
        v = int(val)
        for p in self.parts:
            if p.upper is None or v < p.upper:
                return p.pid
        raise CatalogError(f"Table has no partition for value {v}")

    def prune(self, intervals) -> list:
        """PartitionDefs whose value range intersects the ranger intervals
        (None = no constraint -> all). RANGE prunes by bound overlap; HASH
        prunes only point intervals (ref: rule_partition_processor.go)."""
        if intervals is None:
            return list(self.parts)
        if self.method == "hash":
            pids = []
            for iv in intervals:
                lo, hi = iv.low, iv.high
                if lo is None or hi is None or lo.is_null() or hi.is_null():
                    return list(self.parts)
                if int(lo.val) != int(hi.val) or not (iv.low_inc and iv.high_inc):
                    return list(self.parts)  # only point lookups prune hash
                p = self.parts[int(lo.val) % len(self.parts)]
                if p not in pids:
                    pids.append(p)
            return pids
        out = []
        prev_upper = None
        for p in self.parts:
            lo_b = prev_upper  # inclusive lower bound (None = -inf)
            hi_b = p.upper  # exclusive upper (None = +inf)
            prev_upper = p.upper
            for iv in intervals:
                iv_lo = None if iv.low is None or iv.low.is_null() else int(iv.low.val)
                iv_hi = None if iv.high is None or iv.high.is_null() else int(iv.high.val)
                below = hi_b is not None and iv_lo is not None and iv_lo >= hi_b
                above = lo_b is not None and iv_hi is not None and iv_hi < lo_b
                if not below and not above:
                    out.append(p)
                    break
        return out


@dataclass
class ViewMeta:
    """A stored view: the SELECT text re-plans at every use (ref:
    meta/model ViewInfo; expansion in logical_plan_builder.go's
    buildDataSource view branch)."""

    name: str
    columns: list  # explicit column-name list ([] = from the SELECT)
    select_sql: str


class Catalog:
    """name -> TableMeta, with monotonically increasing table/index ids
    (ref: infoschema; ids from meta's global id allocator)."""

    def __init__(self):
        self._tables: dict[str, TableMeta] = {}  # guarded_by: _lock
        self._next_id = 1001  # guarded_by: _lock
        # RLock: DDL entry points hold it across whole schema changes and
        # re-enter through table() lookups (background TTL/auto-analyze
        # sessions read the same maps from timer threads)
        self._lock = threading.RLock()
        self.version = 0  # schema version (ref: domain schema lease)
        self.databases: set[str] = {"test", "mysql"}  # CREATE/DROP DATABASE
        self.bindings: dict = {}  # GLOBAL plan bindings: digest -> record
        self.stats: dict[int, object] = {}  # table_id -> TableStats (ANALYZE)
        self.views: dict[str, ViewMeta] = {}  # name -> views; guarded_by: _lock
        from .privilege import PrivilegeStore

        self.privileges = PrivilegeStore()  # domain-level user/priv cache
        from .ddl import DDLJobLog

        self.ddl_jobs = DDLJobLog()  # schema-change job history
        from ..util.stmtlog import StmtLog

        self.stmtlog = StmtLog()  # slow-query log + statement summary
        # (domain-level: shared by every session of this catalog)
        from .plancache import PlanCache

        self.plan_cache = PlanCache()  # digest-keyed plan templates
        # (ISSUE 15; instance-level like the reference's plan cache)
        self.bindings_rev = 0  # bumped on GLOBAL binding changes: cached
        # plans were built under a binding view and re-validate against it

    def _alloc_id(self) -> int:  # requires: _lock
        v = self._next_id
        self._next_id += 1
        return v

    def ensure_id_above(self, n: int):
        """Restore installs original table/index ids; the allocator must
        never hand them out again (ref: meta global id rebase)."""
        with self._lock:
            if n >= self._next_id:
                self._next_id = n + 1

    def create_table(self, stmt: A.CreateTableStmt) -> TableMeta:
        name = stmt.table.name.lower()
        with self._lock:
            if name in self.views:
                raise CatalogError(f"view {name!r} already exists")
            if name in self._tables:
                if stmt.if_not_exists:
                    return self._tables[name]
                raise CatalogError(f"table {name!r} already exists")
            cols = []
            handle_col = None
            for i, cd in enumerate(stmt.columns):
                ft = field_type_from_spec(cd.type, cd.not_null or cd.primary_key)
                cols.append(ColumnMeta(
                    cd.name.lower(), i + 1, ft, cd.default, cd.auto_increment,
                    generated=cd.generated,
                    generated_stored=getattr(cd, "generated_stored", False),
                    decl=decl_text(cd.type),
                ))
            pk_cols: list[str] = []
            for cd in stmt.columns:
                if cd.primary_key:
                    ft = next(c for c in cols if c.name == cd.name.lower()).ft
                    if ft.is_int():
                        handle_col = cd.name.lower()
                    else:
                        # NONCLUSTERED primary key: implicit _tidb_rowid
                        # handle + unique PRIMARY index — the reference's
                        # own layout when the PK cannot be the row key
                        # (ref: pkg/meta/model/table.go IsCommonHandle
                        # false path, tables.go AllocHandle)
                        pk_cols = [cd.name.lower()]
            indices = []
            for j, idx in enumerate(getattr(stmt, "indexes", []) or []):
                iname = getattr(idx, "name", "") or f"idx_{j}"
                raw = [c[0].lower() if isinstance(c, tuple) else str(c).lower() for c in idx.columns]
                # expression elements ("__expr__") are dropped; a UNIQUE
                # index that lost one also drops uniqueness — the leftover
                # plain columns would otherwise enforce a STRICTER
                # constraint than declared (reject legal inserts)
                icols = [c for c in raw if c != "__expr__"]
                had_expr = len(icols) != len(raw)
                if getattr(idx, "primary", False):
                    if not icols:
                        continue
                    c = next((c for c in cols if c.name == icols[0]), None)
                    if len(icols) == 1 and c is not None and c.ft.is_int():
                        handle_col = icols[0]
                        continue
                    pk_cols = icols
                    continue
                if not icols:
                    continue  # pure expression index: parsed-and-dropped
                unique = getattr(idx, "unique", False) and not had_expr
                indices.append(IndexMeta(iname, self._alloc_id(), icols, unique))
            if pk_cols and handle_col is None:
                for cn in pk_cols:
                    cm = next((c for c in cols if c.name == cn), None)
                    if cm is None:
                        raise CatalogError(f"unknown PRIMARY KEY column {cn!r}")
                    cm.ft.flag |= Flag.NotNull | Flag.PriKey
                indices.insert(0, IndexMeta("PRIMARY", self._alloc_id(), pk_cols, True))
            part = None
            pdict = (stmt.options or {}).get("partition_by")
            if pdict is not None:
                part = self._build_partition(pdict, cols, handle_col, indices)
            fks = []
            for j, fk in enumerate(getattr(stmt, "foreign_keys", []) or []):
                fks.append(FKMeta(
                    fk.name or f"fk_{j + 1}",
                    [c.lower() for c in fk.columns],
                    fk.ref_table.name.lower(),
                    [c.lower() for c in fk.ref_columns],
                    fk.on_delete, fk.on_update,
                ))
            tbl = TableMeta(name, self._alloc_id(), cols, indices, handle_col, partition=part, foreign_keys=fks)
            self._tables[name] = tbl
            self.version += 1
            return tbl

    def _build_partition(self, pdict: dict, cols, handle_col, indices) -> "PartitionInfo":
        """options['partition_by'] -> PartitionInfo (RANGE / HASH over one
        integer column; ref: ddl partition checks + meta/model
        PartitionInfo). MySQL's unique-key rule is enforced: the partition
        column must be part of the PK / every unique key."""
        method = pdict["method"].lower()
        if method == "key":
            method = "hash"  # KEY(col) hashes the column too
        if method not in ("range", "hash"):
            raise CatalogError(f"PARTITION BY {pdict['method']} not supported yet")
        exprs = pdict.get("exprs") or []
        if len(exprs) != 1 or not isinstance(exprs[0], A.ColumnName):
            raise CatalogError("partitioning supports a single bare column only")
        pcol = exprs[0].name.lower()
        cm = next((c for c in cols if c.name == pcol), None)
        if cm is None:
            raise CatalogError(f"unknown partition column {pcol!r}")
        if not cm.ft.is_int():
            raise CatalogError("partition column must be an integer column")
        # ref: MySQL "A PRIMARY KEY must include all columns in the
        # table's partitioning function" (same for unique keys)
        if handle_col is not None and handle_col != pcol:
            raise CatalogError(
                "a PRIMARY KEY must include the table's partitioning column"
            )
        if indices:
            # same restriction add_index enforces — an inline KEY in the
            # CREATE TABLE must not bypass it (per-partition local indexes
            # are not implemented yet)
            raise CatalogError(
                "secondary indexes on partitioned tables are not supported yet"
            )
        parts = []
        if method == "hash":
            n = int(pdict.get("n") or 0)
            if n <= 0:
                raise CatalogError("PARTITION BY HASH requires PARTITIONS n")
            for i in range(n):
                parts.append(PartitionDef(f"p{i}", self._alloc_id()))
            return PartitionInfo("hash", pcol, parts)
        prev = None
        for pd in pdict.get("parts") or []:
            lt = pd.get("less_than")
            if lt == "MAXVALUE" or (isinstance(lt, list) and lt and lt[0] == "MAXVALUE"):
                upper = None
            else:
                if not (isinstance(lt, list) and len(lt) == 1 and isinstance(lt[0], A.Literal)):
                    raise CatalogError("RANGE partition bounds must be integer literals")
                upper = int(lt[0].value)
                if prev is not None and upper <= prev:
                    raise CatalogError("RANGE partition bounds must be ascending")
                prev = upper
            parts.append(PartitionDef(pd["name"].lower(), self._alloc_id(), upper))
        if not parts:
            raise CatalogError("RANGE partitioning requires a partition list")
        return PartitionInfo("range", pcol, parts)

    def add_index(self, table: str, index_name: str, col_names: list, unique: bool = False, state: str = "public") -> IndexMeta:
        """CREATE INDEX metadata step (the backfill is the session's job —
        ref: pkg/ddl add-index schema change + backfill)."""
        with self._lock:
            tbl = self.table(table)
            if tbl.partition is not None:
                raise CatalogError(
                    "secondary indexes on partitioned tables are not supported yet"
                )
            if any(i.name == index_name for i in tbl.indices):
                raise CatalogError(f"index {index_name!r} already exists")
            raw = [c.lower() for c in col_names]
            col_names = [c for c in raw if c != "__expr__"]
            if not col_names:
                raise CatalogError(
                    "pure expression index has no plain columns (dropped)"
                )
            if len(col_names) != len(raw):
                unique = False  # see create_table: degraded expr index
            for cn in col_names:
                tbl.col(cn)  # validates
            im = IndexMeta(index_name, self._alloc_id(), [c.lower() for c in col_names], unique, state)
            tbl.indices.append(im)
            self.version += 1
            return im

    def drop_index(self, table: str, index_name: str) -> IndexMeta:
        with self._lock:
            tbl = self.table(table)
            im = next((i for i in tbl.indices if i.name == index_name), None)
            if im is None:
                raise CatalogError(f"unknown index {index_name!r} on {table!r}")
            tbl.indices = [i for i in tbl.indices if i is not im]
            self.version += 1
            return im

    def drop_table(self, name: str, if_exists: bool = False):
        with self._lock:
            if name.lower() not in self._tables:
                if name.lower() in self.views:
                    raise CatalogError(f"{name!r} is a VIEW (use DROP VIEW)")
                if if_exists:
                    return
                raise CatalogError(f"unknown table {name!r}")
            meta = self._tables.pop(name.lower())
            self.stats.pop(meta.table_id, None)
            self.version += 1

    def create_view(self, name: str, columns: list, select_sql: str, or_replace: bool = False):
        n = name.lower()
        with self._lock:
            if n in self._tables:
                raise CatalogError(f"table {name!r} already exists")
            if n in self.views and not or_replace:
                raise CatalogError(f"view {name!r} already exists")
            self.views[n] = ViewMeta(n, [c.lower() for c in columns], select_sql)
            self.version += 1

    def drop_view(self, name: str, if_exists: bool = False):
        with self._lock:
            if name.lower() not in self.views:
                if if_exists:
                    return
                raise CatalogError(f"unknown view {name!r}")
            del self.views[name.lower()]
            self.version += 1

    def table_by_id(self, table_id: int) -> TableMeta | None:
        with self._lock:
            return self._table_by_id_locked(table_id)

    def _table_by_id_locked(self, table_id: int):  # requires: _lock
        for t in self._tables.values():
            if t.table_id == table_id:
                return t
        return None

    def table(self, name: str) -> TableMeta:
        with self._lock:
            t = self._tables.get(name.lower())
        if t is None:
            raise CatalogError(f"unknown table {name!r}")
        return t

    def tables(self) -> list:
        with self._lock:
            return sorted(self._tables)

    def view_of(self, name: str):
        """ViewMeta for `name` (None if absent) — the locked lookup every
        cross-thread reader goes through (planner threads vs CREATE/DROP
        VIEW; surfaced by lockwatch on `views`)."""
        with self._lock:
            return self.views.get(name.lower())

    def view_names(self) -> list:
        with self._lock:
            return sorted(self.views)

    def view_snapshot(self) -> list:
        with self._lock:
            return list(self.views.values())
