"""Catalog: table metadata keyed by name — the dict-backed infoschema/meta
analog (ref: pkg/infoschema InfoSchema, pkg/meta/model TableInfo/ColumnInfo;
schema versioning and the domain reload loop collapse to a monotonic version
counter in one process).

CREATE TABLE feeds this from the parsed AST; the planner resolves names
through it; the session allocates row handles from its per-table autoid
(ref: pkg/meta/autoid)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

# handle/col-id allocations are tiny critical sections; one module lock
# keeps TableMeta a plain dataclass (ref: meta/autoid's own mutex)
_ALLOC_LOCK = threading.Lock()

from ..parser import ast as A
from ..types import Collation, FieldType, Flag, TypeCode, new_datetime, new_decimal, new_double, new_longlong, new_varchar


class CatalogError(ValueError):
    pass


def field_type_from_spec(ts: A.TypeSpec, not_null: bool = False) -> FieldType:
    """TypeSpec (DDL/CAST AST) -> FieldType (ref: pkg/parser/types -> tipb
    ColumnInfo mapping in pkg/tablecodec)."""
    name = ts.name
    if name in ("tinyint", "smallint", "mediumint", "int", "bigint", "year", "bit"):
        ft = new_longlong(unsigned=ts.unsigned or name == "bit", notnull=not_null)
        return ft
    if name in ("float", "double"):
        return FieldType(TypeCode.Double, flag=Flag.NotNull if not_null else Flag(0))
    if name == "decimal":
        prec = ts.length if ts.length > 0 else 10
        scale = ts.decimal if ts.decimal >= 0 else 0
        ft = new_decimal(prec, scale)
        if not_null:
            ft = FieldType(ft.tp, ft.flag | Flag.NotNull, ft.flen, ft.decimal)
        return ft
    if name == "json":
        from ..types import new_json

        ft = new_json()
        if not_null:
            ft = FieldType(ft.tp, ft.flag | Flag.NotNull, ft.flen, ft.decimal)
        return ft
    if name in ("enum", "set"):
        from ..types import new_enum, new_set

        mk = new_enum if name == "enum" else new_set
        return mk(tuple(ts.elems), notnull=not_null)
    if name in ("char", "varchar", "binary", "varbinary", "text", "tinytext", "mediumtext", "longtext",
                "blob", "tinyblob", "mediumblob", "longblob"):
        flen = ts.length if ts.length > 0 else 255
        ft = new_varchar(flen)
        if not_null:
            ft = FieldType(ft.tp, ft.flag | Flag.NotNull, ft.flen, ft.decimal, ft.charset, ft.collate)
        return ft
    if name in ("date", "datetime", "timestamp"):
        fsp = ts.decimal if ts.decimal > 0 else 0
        ft = new_datetime(fsp)
        if not_null:
            ft = FieldType(ft.tp, ft.flag | Flag.NotNull, ft.flen, ft.decimal)
        return ft
    if name == "time":  # duration stored as int64 nanoseconds
        return new_longlong(notnull=not_null)
    raise CatalogError(f"unsupported column type {name!r}")


@dataclass
class ColumnMeta:
    name: str
    col_id: int
    ft: FieldType
    default: object = None  # parsed AST default, evaluated at insert
    auto_increment: bool = False
    origin_default: object = None  # Datum filled for rows older than an
    # ADD COLUMN (ref: meta/model ColumnInfo.OriginDefaultValue)


@dataclass
class IndexMeta:
    """(ref: meta/model IndexInfo)."""

    name: str
    index_id: int
    col_names: list
    unique: bool = False


@dataclass
class TableMeta:
    name: str
    table_id: int
    columns: list  # [ColumnMeta]
    indices: list = field(default_factory=list)  # [IndexMeta]
    handle_col: str | None = None  # integer PRIMARY KEY column used as row handle
    _next_handle: int = 1  # autoid allocator cursor (ref: meta/autoid)
    row_count: int = 0  # maintained by DML; the planner's only "statistic"
    next_col_id: int = 0  # max-ever col id + 1: DROP COLUMN must never free
    # its id for reuse (old rows still hold bytes under it)

    def __post_init__(self):
        if self.next_col_id <= 0:
            self.next_col_id = max((c.col_id for c in self.columns), default=0) + 1

    def col(self, name: str) -> ColumnMeta:
        for c in self.columns:
            if c.name == name.lower():
                return c
        raise CatalogError(f"unknown column {name!r} in table {self.name!r}")

    def scan_columns(self) -> tuple:
        """ColumnInfos for a full-row scan of this table."""
        from ..exec.dag import ColumnInfo

        return tuple(ColumnInfo(c.col_id, c.ft, c.origin_default) for c in self.columns)

    def col_ids(self) -> list:
        return [c.col_id for c in self.columns]

    def fts(self) -> list:
        return [c.ft for c in self.columns]

    def alloc_handle(self) -> int:
        with _ALLOC_LOCK:
            h = self._next_handle
            self._next_handle += 1
            return h

    def peek_handle(self) -> int:
        return self._next_handle

    def observe_handle(self, h: int):
        """Explicit-PK inserts advance the allocator past the used value
        (MySQL auto_increment semantics; ref: meta/autoid rebase)."""
        with _ALLOC_LOCK:
            if h >= self._next_handle:
                self._next_handle = h + 1

    def alloc_col_id(self) -> int:
        with _ALLOC_LOCK:
            v = self.next_col_id
            self.next_col_id += 1
            return v


@dataclass
class ViewMeta:
    """A stored view: the SELECT text re-plans at every use (ref:
    meta/model ViewInfo; expansion in logical_plan_builder.go's
    buildDataSource view branch)."""

    name: str
    columns: list  # explicit column-name list ([] = from the SELECT)
    select_sql: str


class Catalog:
    """name -> TableMeta, with monotonically increasing table/index ids
    (ref: infoschema; ids from meta's global id allocator)."""

    def __init__(self):
        self._tables: dict[str, TableMeta] = {}
        self._next_id = 1001
        self._lock = threading.Lock()
        self.version = 0  # schema version (ref: domain schema lease)
        self.stats: dict[int, object] = {}  # table_id -> TableStats (ANALYZE)
        self.views: dict[str, ViewMeta] = {}  # name -> view definition
        from .privilege import PrivilegeStore

        self.privileges = PrivilegeStore()  # domain-level user/priv cache
        from .ddl import DDLJobLog

        self.ddl_jobs = DDLJobLog()  # schema-change job history

    def _alloc_id(self) -> int:
        v = self._next_id
        self._next_id += 1
        return v

    def ensure_id_above(self, n: int):
        """Restore installs original table/index ids; the allocator must
        never hand them out again (ref: meta global id rebase)."""
        with self._lock:
            if n >= self._next_id:
                self._next_id = n + 1

    def create_table(self, stmt: A.CreateTableStmt) -> TableMeta:
        name = stmt.table.name.lower()
        with self._lock:
            if name in self.views:
                raise CatalogError(f"view {name!r} already exists")
            if name in self._tables:
                if stmt.if_not_exists:
                    return self._tables[name]
                raise CatalogError(f"table {name!r} already exists")
            cols = []
            handle_col = None
            for i, cd in enumerate(stmt.columns):
                ft = field_type_from_spec(cd.type, cd.not_null or cd.primary_key)
                cols.append(ColumnMeta(cd.name.lower(), i + 1, ft, cd.default, cd.auto_increment))
                if cd.primary_key:
                    if not ft.is_int():
                        # uniqueness would be silently unenforced otherwise
                        raise CatalogError(
                            "non-integer PRIMARY KEY not supported yet (integer handle columns only)"
                        )
                    handle_col = cd.name.lower()
            indices = []
            for j, idx in enumerate(getattr(stmt, "indexes", []) or []):
                iname = getattr(idx, "name", "") or f"idx_{j}"
                icols = [c[0].lower() if isinstance(c, tuple) else str(c).lower() for c in idx.columns]
                if getattr(idx, "primary", False):
                    c = next((c for c in cols if c.name == icols[0]), None)
                    if len(icols) == 1 and c is not None and c.ft.is_int():
                        handle_col = icols[0]
                        continue
                    raise CatalogError(
                        "non-integer/composite PRIMARY KEY not supported yet (integer handle columns only)"
                    )
                indices.append(IndexMeta(iname, self._alloc_id(), icols, getattr(idx, "unique", False)))
            tbl = TableMeta(name, self._alloc_id(), cols, indices, handle_col)
            self._tables[name] = tbl
            self.version += 1
            return tbl

    def add_index(self, table: str, index_name: str, col_names: list, unique: bool = False) -> IndexMeta:
        """CREATE INDEX metadata step (the backfill is the session's job —
        ref: pkg/ddl add-index schema change + backfill)."""
        with self._lock:
            tbl = self.table(table)
            if any(i.name == index_name for i in tbl.indices):
                raise CatalogError(f"index {index_name!r} already exists")
            for cn in col_names:
                tbl.col(cn)  # validates
            im = IndexMeta(index_name, self._alloc_id(), [c.lower() for c in col_names], unique)
            tbl.indices.append(im)
            self.version += 1
            return im

    def drop_index(self, table: str, index_name: str) -> IndexMeta:
        with self._lock:
            tbl = self.table(table)
            im = next((i for i in tbl.indices if i.name == index_name), None)
            if im is None:
                raise CatalogError(f"unknown index {index_name!r} on {table!r}")
            tbl.indices = [i for i in tbl.indices if i is not im]
            self.version += 1
            return im

    def drop_table(self, name: str, if_exists: bool = False):
        with self._lock:
            if name.lower() not in self._tables:
                if name.lower() in self.views:
                    raise CatalogError(f"{name!r} is a VIEW (use DROP VIEW)")
                if if_exists:
                    return
                raise CatalogError(f"unknown table {name!r}")
            meta = self._tables.pop(name.lower())
            self.stats.pop(meta.table_id, None)
            self.version += 1

    def create_view(self, name: str, columns: list, select_sql: str, or_replace: bool = False):
        n = name.lower()
        with self._lock:
            if n in self._tables:
                raise CatalogError(f"table {name!r} already exists")
            if n in self.views and not or_replace:
                raise CatalogError(f"view {name!r} already exists")
            self.views[n] = ViewMeta(n, [c.lower() for c in columns], select_sql)
            self.version += 1

    def drop_view(self, name: str, if_exists: bool = False):
        with self._lock:
            if name.lower() not in self.views:
                if if_exists:
                    return
                raise CatalogError(f"unknown view {name!r}")
            del self.views[name.lower()]
            self.version += 1

    def table_by_id(self, table_id: int) -> TableMeta | None:
        for t in self._tables.values():
            if t.table_id == table_id:
                return t
        return None

    def table(self, name: str) -> TableMeta:
        t = self._tables.get(name.lower())
        if t is None:
            raise CatalogError(f"unknown table {name!r}")
        return t

    def tables(self) -> list:
        return sorted(self._tables)
