"""Compile expression trees to fused JAX computations.

The reference evaluates expressions either row-at-a-time or vectorized over
chunk columns (ref: pkg/expression/evaluator.go, builtin_*_vec.go). Here the
whole tree compiles into jnp operations over device columns, so XLA fuses the
entire predicate/projection into the surrounding kernel — the TPU-native
version of the "closure executor" fused fast path
(ref: unistore/cophandler/closure_exec.go:165).

Value model: every node yields a CompVal — (value, null) arrays plus the
FieldType. SQL three-valued logic is explicit: `null` is a bool array; the
`value` lane of a NULL slot is unspecified but harmless (kernels mask it).

Class-specific semantics (the tipb ScalarFuncSig split, e.g. GTInt vs GTReal)
are chosen from argument FieldTypes at trace time:

  int       int64 lanes; mixed signed/unsigned compares handled explicitly
  real      float64 lanes (MySQL DOUBLE)
  decimal   int64 lanes scaled by 10^ft.decimal — exact fixed-point
  time      int64 lanes holding the order-preserving packed layout
  string    int64 [N, W+1] packed big-endian words + length (device compare);
            raw bytes ride along for pass-through projection
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..chunk.device import DeviceColumn, pack_string_words
from ..types import Datum, DatumKind, FieldType, MyDecimal, MyTime, TypeCode
from .ir import ColumnRef, Const, Expr, ScalarFunc

# numpy (not jnp) scalar: created at import with no trace/x64-mode
# capture — the jit-purity vet pass enforces this for module constants
I64_MIN = np.int64(-0x8000000000000000)


@dataclass
class CompVal:
    value: jax.Array  # [N] lanes, or [N, W+1] packed words for strings
    null: jax.Array  # bool [N]
    ft: FieldType
    raw: tuple | None = None  # (data[N,W] uint8, length[N] int32) for strings
    const_bytes: bytes | None = None  # set for string CONSTANTS: trace-time
    # values are tracers, so CI guards read the python bytes here

    @property
    def eval_type(self) -> str:
        return self.ft.eval_type()


def _scale(ft: FieldType) -> int:
    return max(ft.decimal, 0)


def _pow10(k: int):
    return jnp.int64(10 ** k)


def _flip(v):
    """Map uint64-bitcast lanes to sign-flipped int64 so signed compare
    gives unsigned order."""
    return v ^ I64_MIN


def _round_div(num, den):
    """Integer divide rounding half away from zero (MySQL decimal/int rules)."""
    sign = jnp.where((num < 0) ^ (den < 0), jnp.int64(-1), jnp.int64(1))
    n, d = jnp.abs(num), jnp.abs(den)
    q = (2 * n + d) // (2 * d)
    return sign * q


def string_bytes(c: CompVal):
    """(data [N, W] uint8, length [N] int32) for a string CompVal — the raw
    bytes when they rode along, else unpacked from the packed compare words
    (which cover the first STRING_WORDS*8 bytes)."""
    if c.raw is not None:
        return c.raw
    words = c.value[:, :-1] ^ I64_MIN  # unflip the sign bit
    length = c.value[:, -1].astype(jnp.int32)
    shifts = jnp.array([56, 48, 40, 32, 24, 16, 8, 0], jnp.int64)
    b = (words[:, :, None] >> shifts[None, None, :]) & 0xFF
    data = b.reshape(words.shape[0], words.shape[1] * 8).astype(jnp.uint8)
    return data, length


def parse_f64_prefix(data, length):
    """MySQL string->double: value of the longest numeric prefix, 0.0 when
    none (ref: pkg/types/convert.go StrToFloat / getValidFloatPrefix —
    leading spaces skipped, trailing garbage ignored, no error here).

    Vectorized byte-at-a-time state machine over the static width W:
    stage 0 leading spaces/sign, 1 sign seen, 2 integer digits, 3 fraction,
    4 exponent sign, 5 exponent digits, 6 done.

    Bit-exact vs strtod on CPU/x64 (mantissa and scale stay exact, division
    is correctly rounded); under TPU f64 emulation the final divide can be
    ~2 ulp off — same deviation class as the double->decimal note below."""
    n, w = data.shape
    ch_all = data.astype(jnp.int32)
    stage = jnp.zeros(n, jnp.int32)
    mant = jnp.zeros(n, jnp.float64)
    frac = jnp.zeros(n, jnp.int32)
    exp = jnp.zeros(n, jnp.int32)
    neg = jnp.zeros(n, bool)
    eneg = jnp.zeros(n, bool)
    seen = jnp.zeros(n, bool)
    for i in range(w):
        ch = ch_all[:, i]
        act = (i < length) & (stage < 6)
        digit = act & (ch >= 48) & (ch <= 57)
        is_sign = (ch == 43) | (ch == 45)
        c_sp = act & (stage == 0) & (ch == 32)
        c_sign = act & (stage == 0) & is_sign
        c_int = digit & (stage <= 2)
        c_dot = act & (stage <= 2) & (ch == 46)
        c_frac = digit & (stage == 3)
        c_e = act & ((stage == 2) | (stage == 3)) & ((ch == 101) | (ch == 69)) & seen
        c_es = act & (stage == 4) & is_sign
        c_exp = digit & ((stage == 4) | (stage == 5))
        matched = c_sp | c_sign | c_int | c_dot | c_frac | c_e | c_es | c_exp
        dv = (ch - 48).astype(jnp.float64)
        mant = jnp.where(c_int | c_frac, mant * 10.0 + dv, mant)
        frac = jnp.where(c_frac, frac + 1, frac)
        exp = jnp.where(c_exp, jnp.minimum(exp * 10 + (ch - 48), 1000), exp)
        neg = neg | (c_sign & (ch == 45))
        eneg = eneg | (c_es & (ch == 45))
        seen = seen | c_int | c_frac
        stage = jnp.where(c_sign, 1, stage)
        stage = jnp.where(c_int, 2, stage)
        stage = jnp.where(c_dot, 3, stage)
        stage = jnp.where(c_e, 4, stage)
        stage = jnp.where(c_es | c_exp, 5, stage)
        stage = jnp.where(act & ~matched, 6, stage)
    e10 = jnp.clip(jnp.where(eneg, -exp, exp) - frac, -400, 400)
    # mant holds an exactly-representable integer (<= ~19 digits drift only
    # beyond 2^53); scale by an exact power of ten — dividing for negative
    # exponents keeps short decimals like "0.5" bit-exact vs strtod, and
    # jnp.power is NOT used (it loses ~1e-8 relative accuracy even in f64)
    p = _pow10_f64(jnp.abs(e10))
    out = jnp.where(e10 >= 0, mant * p, mant / p)
    # MySQL clamps range overflow to +/-DBL_MAX, not inf
    # (ref: pkg/types/convert.go StrToFloat ErrDataOutOfRange handling)
    out = jnp.clip(out, -1.7976931348623157e308, 1.7976931348623157e308)
    return jnp.where(seen, jnp.where(neg, -out, out), 0.0)


def _pow10_f64(ae):
    """Exact-where-possible 10**ae for non-negative int arrays: table lookup
    (10^k is exactly representable for k <= 22) plus exponentiation by
    squaring for the remainder (<= 400)."""
    table = jnp.array([10.0 ** k for k in range(23)], jnp.float64)
    small = jnp.minimum(ae, 22)
    out = table[small]
    r = ae - small
    b = jnp.float64(10.0)
    for _ in range(9):  # rem <= 378 < 2^9
        out = jnp.where((r & 1) == 1, out * b, out)
        b = b * b
        r = r >> 1
    return out


# the civil-calendar math is shared with the host path — branchless, so the
# same functions run on Python ints and int64 lanes (types/mytime.py)
from ..types.mytime import civil_from_days as _ymd_from_days
from ..types.mytime import days_from_civil as _days_from_ymd
from ..types.mytime import days_in_month as _days_in_month_vec


def _ci_ascii_guard(*vals):
    """The device CI kernels fold ASCII only. Column data is screened at
    to_device_batch; CONSTANTS are concrete at trace time and screened
    here — a non-ASCII constant routes the plan to the weight-based
    oracle (NotImplementedError -> the executor's documented fallback)."""
    for v in vals:
        if not isinstance(v, CompVal):
            continue
        b = v.const_bytes
        if b is not None and any(x >= 0x80 for x in b):
            raise NotImplementedError("non-ASCII constant under CI collation (oracle)")


def fold_words_ci(words):
    """ASCII-case-fold packed compare words (a-z -> A-Z), keeping the
    length word — general_ci collation compare on device (ref:
    pkg/util/collate generalCICollator, ASCII subset). Byte-local subtract
    of 0x20 never borrows (0x61-0x20 = 0x41 > 0)."""
    payload = words[..., :-1] ^ I64_MIN
    adj = jnp.zeros_like(payload)
    for b in range(8):
        sh = 56 - 8 * b
        byte = (payload >> sh) & 0xFF
        is_lower = (byte >= 0x61) & (byte <= 0x7A)
        adj = adj + jnp.where(is_lower, jnp.int64(0x20) << sh, jnp.int64(0))
    return jnp.concatenate([(payload - adj) ^ I64_MIN, words[..., -1:]], axis=-1)


def _words_cmp(a, b):
    """Lexicographic compare of [N, W] int64 word arrays -> (-1/0/1)[N]."""
    neq = a != b
    any_neq = neq.any(axis=-1)
    idx = jnp.argmax(neq, axis=-1)
    av = jnp.take_along_axis(a, idx[:, None], axis=-1)[:, 0]
    bv = jnp.take_along_axis(b, idx[:, None], axis=-1)[:, 0]
    lt = any_neq & (av < bv)
    gt = any_neq & (av > bv)
    return jnp.where(lt, -1, jnp.where(gt, 1, 0)).astype(jnp.int32)


def normalize_device_column(c: DeviceColumn) -> CompVal:
    """DeviceColumn -> CompVal (strings get packed compare words)."""
    if c.is_varlen():
        words = pack_string_words(c.data, c.length)
        return CompVal(words, c.null, c.ft, raw=(c.data, c.length))
    data = c.data
    if data.dtype != jnp.int64 and c.ft.eval_type() != "real":
        data = data.astype(jnp.int64)
    return CompVal(data, c.null, c.ft)


class ExprCompiler:
    """Compiles Expr trees against a fixed input schema."""

    def __init__(self, input_fts: list[FieldType]):
        self.input_fts = input_fts

    # -- entry ---------------------------------------------------------------
    def run(self, exprs: list[Expr], cols: list[DeviceColumn]) -> list[CompVal]:
        """Trace `exprs` over device columns (called inside jit)."""
        self._cols = cols
        self._n = cols[0].null.shape[0] if cols else 1
        self._col_cache: dict[int, CompVal] = {}
        return [self._eval(e) for e in exprs]

    # -- dispatch ------------------------------------------------------------
    def _eval(self, e: Expr) -> CompVal:
        if isinstance(e, ColumnRef):
            return self._column(e)
        if isinstance(e, Const):
            return self._const(e)
        if isinstance(e, ScalarFunc):
            fn = getattr(self, f"_op_{e.op}", None)
            if fn is None:
                raise NotImplementedError(f"scalar op {e.op} not implemented on device")
            return fn(e)
        raise TypeError(f"unknown expr node {e!r}")

    def _column(self, e: ColumnRef) -> CompVal:
        if e.index in self._col_cache:
            return self._col_cache[e.index]
        c = self._cols[e.index]
        if isinstance(c, CompVal):
            # pipeline stages (exec/builder.py) bind already-normalized values
            self._col_cache[e.index] = c
            return c
        v = normalize_device_column(c)
        self._col_cache[e.index] = v
        return v

    def _const(self, e: Const) -> CompVal:
        n = self._n
        d = e.datum
        if d.is_null():
            et = e.ft.eval_type()
            dt = jnp.float64 if et == "real" else jnp.int64
            return CompVal(jnp.zeros(n, dt), jnp.ones(n, bool), e.ft)
        et = e.ft.eval_type()
        if et == "real":
            v = jnp.full(n, float(d.val), jnp.float64)
        elif et == "decimal":
            dec = d.val if isinstance(d.val, MyDecimal) else MyDecimal(d.val)
            v = jnp.full(n, dec.to_scaled_int(_scale(e.ft)), jnp.int64)
        elif et == "time":
            packed = d.val.packed if isinstance(d.val, MyTime) else int(d.val)
            v = jnp.full(n, packed, jnp.int64)
        elif et == "string":
            b = d.val.encode() if isinstance(d.val, str) else bytes(d.val)
            import numpy as np

            w = max(1, len(b))
            data = np.zeros((1, w), np.uint8)
            data[0, : len(b)] = np.frombuffer(b, np.uint8)
            words = pack_string_words(jnp.asarray(data), jnp.asarray(np.array([len(b)], np.int32)))
            v = jnp.broadcast_to(words, (n, words.shape[1]))
            return CompVal(v, jnp.zeros(n, bool), e.ft,
                           raw=(jnp.broadcast_to(jnp.asarray(data), (n, w)), jnp.full(n, len(b), jnp.int32)),
                           const_bytes=b)
        else:
            v = jnp.full(n, int(d.val), jnp.int64)
        return CompVal(v, jnp.zeros(n, bool), e.ft)

    # -- coercion ------------------------------------------------------------
    @staticmethod
    def _common_class(a: CompVal, b: CompVal) -> str:
        ea, eb = a.eval_type, b.eval_type
        if "string" in (ea, eb) and ea == eb:
            return "string"
        if "real" in (ea, eb):
            return "real"
        if "decimal" in (ea, eb):
            return "decimal"
        if "time" in (ea, eb):
            return "time"
        return "int"

    def _to_class(self, v: CompVal, cls: str, scale: int | None = None) -> CompVal:
        et = v.eval_type
        if cls == "real":
            if et == "real":
                return v
            if et == "string":
                data, length = string_bytes(v)
                return CompVal(parse_f64_prefix(data, length), v.null, FieldType(TypeCode.Double))
            if et == "decimal":
                return CompVal(v.value.astype(jnp.float64) / float(10 ** _scale(v.ft)), v.null, FieldType(TypeCode.Double))
            if v.ft.is_unsigned():
                # uint64 bit-pattern -> f64 without sign error
                val = v.value
                as_f = jnp.where(val >= 0, val.astype(jnp.float64), val.astype(jnp.float64) + 2.0**64)
                return CompVal(as_f, v.null, FieldType(TypeCode.Double))
            return CompVal(v.value.astype(jnp.float64), v.null, FieldType(TypeCode.Double))
        if cls == "decimal":
            s = _scale(v.ft) if scale is None else scale
            if et == "string":
                # via double (MySQL parses the numeric prefix first)
                v = self._to_class(v, "real")
                et = "real"
            if et == "decimal":
                return self._rescale_dec(v, s)
            if et == "int":
                from ..types import new_decimal

                ft = new_decimal(20, 0)
                vv = CompVal(v.value, v.null, ft)
                return self._rescale_dec(vv, s)
            if et == "real":
                ft = FieldType(TypeCode.NewDecimal, decimal=s)
                x = v.value * float(10 ** s)
                # MySQL rounds half away from zero, not half-to-even.
                # KNOWN DEVIATION: MySQL/TiDB convert double->decimal via the
                # shortest decimal repr (so the double nearest 16.405 rounds
                # like "16.405"); this kernel rounds the binary value, which
                # can differ by 1 ulp of the target scale on repr midpoints.
                scaled = jnp.where(x >= 0, jnp.floor(x + 0.5), jnp.ceil(x - 0.5)).astype(jnp.int64)
                return CompVal(scaled, v.null, ft)
        if cls in ("int", "time"):
            return v
        raise NotImplementedError(f"coerce {et} -> {cls}")

    @staticmethod
    def _rescale_dec(v: CompVal, s: int) -> CompVal:
        cur = _scale(v.ft)
        ft = v.ft.clone()
        ft.tp = TypeCode.NewDecimal
        ft.decimal = s
        if s == cur:
            return CompVal(v.value, v.null, ft)
        if s > cur:
            return CompVal(v.value * _pow10(s - cur), v.null, ft)
        return CompVal(_round_div(v.value, _pow10(cur - s)), v.null, ft)

    # -- arithmetic ----------------------------------------------------------
    def _arith(self, e: ScalarFunc, int_fn, real_fn, dec_fn):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        cls = self._common_class(a, b)
        null = a.null | b.null
        if cls == "real":
            a, b = self._to_class(a, "real"), self._to_class(b, "real")
            out = real_fn(a.value, b.value)
            return CompVal(out, null, e.ft)
        if cls == "decimal":
            return dec_fn(a, b, null, e.ft)
        out = int_fn(a.value, b.value)
        return CompVal(out, null, e.ft)

    def _dec_addsub(self, sign: int):
        def fn(a: CompVal, b: CompVal, null, ft):
            s = max(_scale(a.ft), _scale(b.ft))
            av = self._to_class(a, "decimal", s).value
            bv = self._to_class(b, "decimal", s).value
            out = av + sign * bv
            return self._rescale_dec(CompVal(out, null, FieldType(TypeCode.NewDecimal, decimal=s)), _scale(ft))

        return fn

    def _op_plus(self, e):
        return self._arith(e, lambda a, b: a + b, lambda a, b: a + b, self._dec_addsub(1))

    def _op_minus(self, e):
        return self._arith(e, lambda a, b: a - b, lambda a, b: a - b, self._dec_addsub(-1))

    def _op_mul(self, e):
        def dec(a: CompVal, b: CompVal, null, ft):
            av, bv = self._to_class(a, "decimal"), self._to_class(b, "decimal")
            s = _scale(av.ft) + _scale(bv.ft)
            out = av.value * bv.value
            return self._rescale_dec(CompVal(out, null, FieldType(TypeCode.NewDecimal, decimal=s)), _scale(ft))

        return self._arith(e, lambda a, b: a * b, lambda a, b: a * b, dec)

    def _op_div(self, e):
        """`/`: reals divide; ints & decimals use decimal division with the
        +4 scale increment (ref: cop_handler.go:350-354, mydecimal DivFracIncr).
        Division by zero yields NULL."""
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        if self._common_class(a, b) == "real":
            a, b = self._to_class(a, "real"), self._to_class(b, "real")
            zero = b.value == 0.0
            null = a.null | b.null | zero
            out = a.value / jnp.where(zero, 1.0, b.value)
            return CompVal(out, null, e.ft)
        av, bv = self._to_class(a, "decimal"), self._to_class(b, "decimal")
        sr = _scale(e.ft)
        k = sr - _scale(av.ft) + _scale(bv.ft)
        zero = bv.value == 0
        null = a.null | b.null | zero
        num = av.value * _pow10(max(k, 0))
        den = jnp.where(zero, jnp.int64(1), bv.value)
        out = _round_div(num, den)
        if k < 0:
            out = _round_div(out, _pow10(-k))
        return CompVal(out, null, e.ft)

    def _op_intdiv(self, e):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        if self._common_class(a, b) == "real":
            av, bv = self._to_class(a, "real"), self._to_class(b, "real")
            zero = bv.value == 0.0
            null = a.null | b.null | zero
            q = av.value / jnp.where(zero, 1.0, bv.value)
            out = jnp.trunc(q).astype(jnp.int64)
            return CompVal(out, null, e.ft)
        if self._common_class(a, b) == "decimal":
            av, bv = self._to_class(a, "decimal"), self._to_class(b, "decimal")
            zero = bv.value == 0
            null = a.null | b.null | zero
            sa, sb = _scale(av.ft), _scale(bv.ft)
            num, den = av.value * _pow10(sb), bv.value * _pow10(sa)
            den = jnp.where(zero, jnp.int64(1), den)
            q = jnp.abs(num) // jnp.abs(den)  # truncate toward zero
            out = jnp.where((num < 0) ^ (den < 0), -q, q)
            return CompVal(out, null, e.ft)
        zero = b.value == 0
        null = a.null | b.null | zero
        den = jnp.where(zero, jnp.int64(1), b.value)
        q = jnp.abs(a.value) // jnp.abs(den)
        out = jnp.where((a.value < 0) ^ (den < 0), -q, q)
        return CompVal(out, null, e.ft)

    def _op_mod(self, e):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        if self._common_class(a, b) == "real":
            a, b = self._to_class(a, "real"), self._to_class(b, "real")
            zero = b.value == 0.0
            null = a.null | b.null | zero
            out = jnp.fmod(a.value, jnp.where(zero, 1.0, b.value))
            return CompVal(out, null, e.ft)
        if self._common_class(a, b) == "decimal":
            s = max(_scale(a.ft), _scale(b.ft))
            av = self._to_class(a, "decimal", s).value
            bv = self._to_class(b, "decimal", s).value
            zero = bv == 0
            null = a.null | b.null | zero
            den = jnp.where(zero, jnp.int64(1), bv)
            r = jnp.abs(av) % jnp.abs(den)
            out = jnp.where(av < 0, -r, r)  # MySQL mod takes dividend sign
            return CompVal(out, null, e.ft)
        zero = b.value == 0
        null = a.null | b.null | zero
        den = jnp.where(zero, jnp.int64(1), b.value)
        r = jnp.abs(a.value) % jnp.abs(den)
        out = jnp.where(a.value < 0, -r, r)
        return CompVal(out, null, e.ft)

    def _op_unaryminus(self, e):
        a = self._eval(e.args[0])
        return CompVal(-a.value, a.null, e.ft)

    def _op_abs(self, e):
        a = self._eval(e.args[0])
        return CompVal(jnp.abs(a.value), a.null, e.ft)

    # -- comparison ----------------------------------------------------------
    def _cmp(self, a: CompVal, b: CompVal):
        """Return (-1/0/1)[N] semantic comparison of a vs b."""
        cls = self._common_class(a, b)
        if cls == "string":
            av, bv = a.value, b.value
            if a.ft.is_ci() or b.ft.is_ci():
                _ci_ascii_guard(a, b)
                av, bv = fold_words_ci(av), fold_words_ci(bv)
            return _words_cmp(av, bv)
        if cls == "real":
            av, bv = self._to_class(a, "real").value, self._to_class(b, "real").value
            return (jnp.sign(av - bv)).astype(jnp.int32)
        if cls == "decimal":
            s = max(_scale(a.ft), _scale(b.ft))
            av = self._to_class(a, "decimal", s).value
            bv = self._to_class(b, "decimal", s).value
            return jnp.sign(av - bv).astype(jnp.int32)
        # int class: handle signedness (ref: builtin_compare.go CompareInt)
        au, bu = a.ft.is_unsigned(), b.ft.is_unsigned()
        av, bv = a.value, b.value
        if au and bu:
            av, bv = _flip(av), _flip(bv)
            return jnp.where(av < bv, -1, jnp.where(av > bv, 1, 0)).astype(jnp.int32)
        if not au and not bu:
            return jnp.where(av < bv, -1, jnp.where(av > bv, 1, 0)).astype(jnp.int32)
        if au and not bu:
            # a unsigned vs b signed: b<0 => a>b; else unsigned compare
            c = jnp.where(_flip(av) < _flip(bv), -1, jnp.where(_flip(av) > _flip(bv), 1, 0))
            return jnp.where(bv < 0, 1, c).astype(jnp.int32)
        c = jnp.where(_flip(av) < _flip(bv), -1, jnp.where(_flip(av) > _flip(bv), 1, 0))
        return jnp.where(av < 0, -1, c).astype(jnp.int32)

    def _cmp_op(self, e: ScalarFunc, pred):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        c = self._cmp(a, b)
        out = pred(c).astype(jnp.int64)
        return CompVal(out, a.null | b.null, e.ft)

    def _op_eq(self, e):
        return self._cmp_op(e, lambda c: c == 0)

    def _op_ne(self, e):
        return self._cmp_op(e, lambda c: c != 0)

    def _op_lt(self, e):
        return self._cmp_op(e, lambda c: c < 0)

    def _op_le(self, e):
        return self._cmp_op(e, lambda c: c <= 0)

    def _op_gt(self, e):
        return self._cmp_op(e, lambda c: c > 0)

    def _op_ge(self, e):
        return self._cmp_op(e, lambda c: c >= 0)

    def _op_nulleq(self, e):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        c = self._cmp(a, b)
        both_null = a.null & b.null
        eq = (c == 0) & ~a.null & ~b.null
        return CompVal((both_null | eq).astype(jnp.int64), jnp.zeros_like(a.null), e.ft)

    def _op_in(self, e):
        a = self._eval(e.args[0])
        hit = jnp.zeros(self._n, bool)
        any_null = jnp.zeros(self._n, bool)
        for arg in e.args[1:]:
            b = self._eval(arg)
            c = self._cmp(a, b)
            hit = hit | ((c == 0) & ~b.null)
            any_null = any_null | b.null
        # a NULL lane's value is garbage — never let it match
        hit = hit & ~a.null
        # NULL if lhs null, or no hit with some NULL operand (MySQL IN)
        null = a.null | (~hit & any_null)
        return CompVal(hit.astype(jnp.int64), null, e.ft)

    def _op_between(self, e):
        a, lo, hi = (self._eval(x) for x in e.args)
        c1, c2 = self._cmp(a, lo), self._cmp(a, hi)
        out = ((c1 >= 0) & (c2 <= 0)).astype(jnp.int64)
        return CompVal(out, a.null | lo.null | hi.null, e.ft)

    # -- logical -------------------------------------------------------------
    @staticmethod
    def _truth(v: CompVal):
        """MySQL truthiness of a value lane (nonzero = true)."""
        if v.eval_type == "real":
            return v.value != 0.0
        if v.value.ndim == 2:
            # MySQL string truthiness parses a leading number ('0'→false,
            # 'abc'→false); no device parse yet, so refuse pushdown — the
            # whitelist gate routes these to the host path.
            raise NotImplementedError("logical op over string operand not on device")
        return v.value != 0

    @staticmethod
    def _sel(cond, a: CompVal, b: CompVal, av, bv):
        """jnp.where that handles 2-D string word lanes and carries raw."""
        if av.ndim == 2:
            out = jnp.where(cond[:, None], av, bv)
            raw = None
            if a.raw is not None and b.raw is not None:
                ad, al = a.raw
                bd, bl = b.raw
                w = max(ad.shape[1], bd.shape[1])
                if ad.shape[1] < w:
                    ad = jnp.pad(ad, ((0, 0), (0, w - ad.shape[1])))
                if bd.shape[1] < w:
                    bd = jnp.pad(bd, ((0, 0), (0, w - bd.shape[1])))
                raw = (jnp.where(cond[:, None], ad, bd), jnp.where(cond, al, bl))
            return out, raw
        return jnp.where(cond, av, bv), None

    def _op_and(self, e):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        ta, tb = self._truth(a), self._truth(b)
        f = (~ta & ~a.null) | (~tb & ~b.null)
        null = ~f & (a.null | b.null)
        return CompVal((~f & ~null).astype(jnp.int64), null, e.ft)

    def _op_or(self, e):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        ta, tb = self._truth(a), self._truth(b)
        t = (ta & ~a.null) | (tb & ~b.null)
        null = ~t & (a.null | b.null)
        return CompVal(t.astype(jnp.int64), null, e.ft)

    def _op_not(self, e):
        a = self._eval(e.args[0])
        return CompVal((~self._truth(a)).astype(jnp.int64), a.null, e.ft)

    def _op_xor(self, e):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        out = (self._truth(a) ^ self._truth(b)).astype(jnp.int64)
        return CompVal(out, a.null | b.null, e.ft)

    # -- null handling / control ---------------------------------------------
    def _op_isnull(self, e):
        a = self._eval(e.args[0])
        return CompVal(a.null.astype(jnp.int64), jnp.zeros_like(a.null), e.ft)

    def _op_ifnull(self, e):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        av = self._coerce_result(a, e.ft).value
        bv = self._coerce_result(b, e.ft).value
        out, raw = self._sel(~a.null, a, b, av, bv)
        return CompVal(out, a.null & b.null, e.ft, raw=raw)

    def _op_if(self, e):
        c, a, b = (self._eval(x) for x in e.args)
        cond = self._truth(c) & ~c.null
        av = self._coerce_result(a, e.ft).value
        bv = self._coerce_result(b, e.ft).value
        out, raw = self._sel(cond, a, b, av, bv)
        null = jnp.where(cond, a.null, b.null)
        return CompVal(out, null, e.ft, raw=raw)

    def _op_case(self, e):
        """case [when1, then1, when2, then2, ..., else?]."""
        args = e.args
        pairs = []
        i = 0
        while i + 1 < len(args):
            pairs.append((args[i], args[i + 1]))
            i += 2
        els = self._eval(args[i]) if i < len(args) else None
        if els is not None:
            out = self._coerce_result(els, e.ft).value
            null = els.null
        else:
            dt = jnp.float64 if e.ft.eval_type() == "real" else jnp.int64
            out = jnp.zeros(self._n, dt)
            null = jnp.ones(self._n, bool)
        for cond_e, then_e in reversed(pairs):
            c = self._eval(cond_e)
            t = self._eval(then_e)
            hit = self._truth(c) & ~c.null
            tv = self._coerce_result(t, e.ft).value
            cond2 = hit[:, None] if tv.ndim == 2 else hit
            out = jnp.where(cond2, tv, out)
            null = jnp.where(hit, t.null, null)
        return CompVal(out, null, e.ft)

    def _op_coalesce(self, e):
        vals = [self._eval(a) for a in e.args]
        out = self._coerce_result(vals[-1], e.ft).value
        null = vals[-1].null
        for v in reversed(vals[:-1]):
            vv = self._coerce_result(v, e.ft).value
            cond = v.null[:, None] if vv.ndim == 2 else v.null
            out = jnp.where(cond, out, vv)
            null = jnp.where(v.null, null, jnp.zeros_like(null))
        return CompVal(out, null, e.ft)

    def _coerce_result(self, v: CompVal, ft: FieldType) -> CompVal:
        cls = ft.eval_type()
        if cls == "decimal":
            return self._to_class(v, "decimal", _scale(ft))
        if cls == "real":
            return self._to_class(v, "real")
        return v

    # -- cast ----------------------------------------------------------------
    def _op_cast(self, e):
        a = self._eval(e.args[0])
        src, dst = a.eval_type, e.ft.eval_type()
        if dst == "real":
            return CompVal(self._to_class(a, "real").value, a.null, e.ft)
        if dst == "decimal":
            return CompVal(self._to_class(a, "decimal", _scale(e.ft)).value, a.null, e.ft)
        if dst == "int":
            if src == "string":
                a = self._to_class(a, "real")
                src = "real"
            if src == "real":
                out = jnp.round(a.value).astype(jnp.int64)  # MySQL rounds
                return CompVal(out, a.null, e.ft)
            if src == "decimal":
                out = _round_div(a.value, _pow10(_scale(a.ft)))
                return CompVal(out, a.null, e.ft)
            return CompVal(a.value, a.null, e.ft)
        if dst == "time" and src == "time":
            return CompVal(a.value, a.null, e.ft)
        if dst == "string" and src == "string":
            return CompVal(a.value, a.null, e.ft, raw=a.raw)
        raise NotImplementedError(f"cast {src} -> {dst} not on device")

    # -- math ----------------------------------------------------------------
    def _op_ceil(self, e):
        a = self._eval(e.args[0])
        if a.eval_type == "real":
            return CompVal(jnp.ceil(a.value), a.null, e.ft)
        if a.eval_type == "decimal":
            p = _pow10(_scale(a.ft))
            q = jnp.where(a.value >= 0, (a.value + p - 1) // p, -((-a.value) // p))
            return CompVal(q, a.null, e.ft)
        return CompVal(a.value, a.null, e.ft)

    def _op_floor(self, e):
        a = self._eval(e.args[0])
        if a.eval_type == "real":
            return CompVal(jnp.floor(a.value), a.null, e.ft)
        if a.eval_type == "decimal":
            p = _pow10(_scale(a.ft))
            q = jnp.where(a.value >= 0, a.value // p, -((-a.value + p - 1) // p))
            return CompVal(q, a.null, e.ft)
        return CompVal(a.value, a.null, e.ft)

    def _op_round(self, e):
        a = self._eval(e.args[0])
        nd = 0
        if len(e.args) > 1:
            c = e.args[1]
            if isinstance(c, Const) and not c.datum.is_null():
                nd = int(c.datum.val)
            else:
                raise NotImplementedError("round with non-constant digits")
        if a.eval_type == "real":
            p = float(10 ** nd)
            v = a.value * p
            out = jnp.where(v >= 0, jnp.floor(v + 0.5), jnp.ceil(v - 0.5)) / p
            return CompVal(out, a.null, e.ft)
        if a.eval_type == "decimal":
            tgt = min(max(nd, 0), _scale(a.ft))
            r = self._rescale_dec(a, tgt)
            return CompVal(self._rescale_dec(r, _scale(e.ft)).value, a.null, e.ft)
        if nd >= 0:
            return CompVal(a.value, a.null, e.ft)
        p = _pow10(-nd)
        return CompVal(_round_div(a.value, p) * p, a.null, e.ft)

    def _op_sqrt(self, e):
        a = self._to_class(self._eval(e.args[0]), "real")
        neg = a.value < 0
        out = jnp.sqrt(jnp.where(neg, 0.0, a.value))
        return CompVal(out, a.null | neg, e.ft)

    def _op_exp(self, e):
        a = self._to_class(self._eval(e.args[0]), "real")
        return CompVal(jnp.exp(a.value), a.null, e.ft)

    def _op_ln(self, e):
        a = self._to_class(self._eval(e.args[0]), "real")
        bad = a.value <= 0
        return CompVal(jnp.log(jnp.where(bad, 1.0, a.value)), a.null | bad, e.ft)

    _op_log = _op_ln

    def _op_pow(self, e):
        a = self._to_class(self._eval(e.args[0]), "real")
        b = self._to_class(self._eval(e.args[1]), "real")
        return CompVal(jnp.power(a.value, b.value), a.null | b.null, e.ft)

    def _op_sign(self, e):
        a = self._eval(e.args[0])
        out = jnp.sign(a.value).astype(jnp.int64)
        return CompVal(out, a.null, e.ft)

    # -- bit ops (int64 lanes) -----------------------------------------------
    def _bitop(self, e, fn):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        return CompVal(fn(a.value, b.value), a.null | b.null, e.ft)

    def _op_bitand(self, e):
        return self._bitop(e, lambda a, b: a & b)

    def _op_bitor(self, e):
        return self._bitop(e, lambda a, b: a | b)

    def _op_bitxor(self, e):
        return self._bitop(e, lambda a, b: a ^ b)

    def _op_bitneg(self, e):
        a = self._eval(e.args[0])
        return CompVal(~a.value, a.null, e.ft)

    def _op_shiftleft(self, e):
        return self._bitop(e, lambda a, b: jnp.where((b >= 64) | (b < 0), jnp.int64(0), a << jnp.clip(b, 0, 63)))

    def _op_shiftright(self, e):
        # logical (unsigned) shift, as MySQL >> on BIGINT UNSIGNED
        return self._bitop(
            e,
            lambda a, b: jnp.where(
                (b >= 64) | (b < 0),
                jnp.int64(0),
                (a.astype(jnp.uint64) >> jnp.clip(b, 0, 63).astype(jnp.uint64)).astype(jnp.int64),
            ),
        )

    # -- string --------------------------------------------------------------
    def _op_length(self, e):
        a = self._eval(e.args[0])
        if a.raw is None:
            raise NotImplementedError("length() needs raw string column")
        return CompVal(a.raw[1].astype(jnp.int64), a.null, e.ft)

    def _op_strcmp(self, e):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])
        av, bv = a.value, b.value
        if a.ft.is_ci() or b.ft.is_ci():
            _ci_ascii_guard(a, b)
            av, bv = fold_words_ci(av), fold_words_ci(bv)
        return CompVal(_words_cmp(av, bv).astype(jnp.int64), a.null | b.null, e.ft)

    def _op_like(self, e):
        """LIKE with constant pattern; device support for exact / 'prefix%' /
        '%suffix' is TODO — currently exact and prefix% patterns."""
        a = self._eval(e.args[0])
        pat = e.args[1]
        if not isinstance(pat, Const):
            raise NotImplementedError("LIKE with non-constant pattern")
        p = pat.datum.val
        p = p if isinstance(p, str) else p.decode()
        if a.raw is None:
            raise NotImplementedError("LIKE needs raw string column")
        data, length = a.raw
        if a.ft.is_ci() or pat.ft.is_ci():
            # general_ci LIKE: ASCII fold on BOTH sides (matching the
            # compare()/sort-key fold); a non-ASCII pattern goes to the
            # weight-based oracle
            from ..expr.eval_ref import _ascii_upper

            if any(ord(c) >= 0x80 for c in p):
                raise NotImplementedError("non-ASCII CI LIKE pattern (oracle)")
            hit = (data >= 0x61) & (data <= 0x7A)
            data = jnp.where(hit, data - 0x20, data)
            p = _ascii_upper(p)
        import numpy as np

        if p.endswith("%") and "%" not in p[:-1] and "_" not in p:
            prefix = p[:-1].encode()
            out = self._prefix_match(data, length, prefix)
        elif "%" not in p and "_" not in p:
            exact = p.encode()
            out = self._prefix_match(data, length, exact) & (length == len(exact))
        else:
            raise NotImplementedError(f"LIKE pattern {p!r} not on device yet")
        return CompVal(out.astype(jnp.int64), a.null, e.ft)

    @staticmethod
    def _prefix_match(data, length, prefix: bytes):
        import numpy as np

        k = len(prefix)
        if k == 0:
            return jnp.ones(data.shape[0], bool)
        w = data.shape[1]
        if k > w:
            return jnp.zeros(data.shape[0], bool)
        pref = jnp.asarray(np.frombuffer(prefix, np.uint8))
        eq = (data[:, :k] == pref[None, :]).all(axis=1)
        return eq & (length >= k)

    def _op_substr(self, e):
        """SUBSTR(s, pos[, len]) — per-row byte shift via gather."""
        a = self._eval(e.args[0])
        data, length = string_bytes(a)
        pos_cv = self._eval(e.args[1])
        pos = pos_cv.value.astype(jnp.int32)
        null = a.null | pos_cv.null
        # MySQL: 1-based; negative counts from the end; 0 -> ''
        start = jnp.where(pos > 0, pos - 1, length + pos)
        bad = (pos == 0) | (start < 0)
        start = jnp.clip(start, 0, length)
        avail = jnp.maximum(length - start, 0)
        if len(e.args) > 2:
            want_cv = self._eval(e.args[2])
            null = null | want_cv.null
            new_len = jnp.clip(want_cv.value.astype(jnp.int32), 0, avail)
        else:
            new_len = avail
        new_len = jnp.where(bad, 0, new_len)
        w = data.shape[1]
        idx = jnp.clip(jnp.arange(w)[None, :] + start[:, None], 0, w - 1)
        shifted = jnp.take_along_axis(data, idx, axis=1)
        shifted = jnp.where(jnp.arange(w)[None, :] < new_len[:, None], shifted, 0)
        return self._string_result(shifted, new_len, null, e.ft)

    def _string_result(self, data, length, null, ft):
        return CompVal(pack_string_words(data, length), null, ft, raw=(data, length))

    def _op_upper(self, e):
        return self._case_fold(e, upper=True)

    def _op_lower(self, e):
        return self._case_fold(e, upper=False)

    def _case_fold(self, e, upper: bool):
        a = self._eval(e.args[0])
        data, length = string_bytes(a)
        if upper:
            hit = (data >= 0x61) & (data <= 0x7A)
            out = jnp.where(hit, data - 0x20, data)
        else:
            hit = (data >= 0x41) & (data <= 0x5A)
            out = jnp.where(hit, data + 0x20, data)
        return self._string_result(out, length, a.null, e.ft)

    def _op_concat(self, e):
        """CONCAT(...) — pairwise fold; NULL if any arg NULL (MySQL)."""
        args = [self._as_string(self._eval(x)) for x in e.args]
        out = args[0]
        for b in args[1:]:
            out = self._concat2(out, b)
        d, ln = out.raw
        return self._string_result(d, ln, out.null, e.ft)

    def _as_string(self, a: CompVal) -> CompVal:
        if a.value.ndim == 2:
            data, length = string_bytes(a)
            return CompVal(a.value, a.null, a.ft, raw=(data, length))
        raise NotImplementedError("concat of non-string operands on device (cast first)")

    @staticmethod
    def _concat2(a: CompVal, b: CompVal) -> CompVal:
        da, la = a.raw
        db, lb = b.raw
        wa, wb = da.shape[1], db.shape[1]
        w = wa + wb
        pos = jnp.arange(w)[None, :]
        a_pad = jnp.pad(da, ((0, 0), (0, w - wa)))
        b_pad = jnp.pad(db, ((0, 0), (0, w - wb)))
        from_b_idx = jnp.clip(pos - la[:, None], 0, w - 1)
        b_shift = jnp.take_along_axis(b_pad, from_b_idx, axis=1)
        out = jnp.where(pos < la[:, None], a_pad, b_shift)
        ln = la + lb
        out = jnp.where(pos < ln[:, None], out, 0)
        return CompVal(a.value, a.null | b.null, a.ft, raw=(out, ln.astype(jnp.int32)))

    def _op_trim(self, e):
        return self._trim(e, left=True, right=True)

    def _op_ltrim(self, e):
        return self._trim(e, left=True, right=False)

    def _op_rtrim(self, e):
        return self._trim(e, left=False, right=True)

    def _trim(self, e, left: bool, right: bool):
        a = self._eval(e.args[0])
        data, length = string_bytes(a)
        w = data.shape[1]
        pos = jnp.arange(w)[None, :]
        in_str = pos < length[:, None]
        is_sp = (data == 0x20) & in_str
        lead = jnp.zeros(data.shape[0], jnp.int32)
        if left:
            # leading spaces: cumulative product of the space mask
            run = jnp.cumprod(jnp.where(in_str, is_sp, True).astype(jnp.int32), axis=1)
            lead = jnp.minimum((run * in_str.astype(jnp.int32)).sum(axis=1), length)
        trail = jnp.zeros(data.shape[0], jnp.int32)
        if right:
            # walk from the end: src index for the k-th-from-last byte
            src = length[:, None] - 1 - pos
            rev_bytes = jnp.take_along_axis(data, jnp.clip(src, 0, w - 1), axis=1)
            is_sp_end = jnp.where(src >= 0, rev_bytes == 0x20, False)
            run_t = jnp.cumprod(is_sp_end.astype(jnp.int32), axis=1)
            trail = jnp.minimum(run_t.sum(axis=1), length)
        new_len = jnp.maximum(length - lead - trail, 0)
        idx = jnp.clip(pos + lead[:, None], 0, w - 1)
        shifted = jnp.take_along_axis(data, idx, axis=1)
        shifted = jnp.where(pos < new_len[:, None], shifted, 0)
        return self._string_result(shifted, new_len.astype(jnp.int32), a.null, e.ft)

    def _op_replace(self, e):
        raise NotImplementedError("replace() is host-only (data-dependent lengths); planner keeps it at root")

    # -- date arithmetic (vectorized civil-calendar math) ---------------------
    def _op_date_add(self, e):
        return self._date_shift(e, +1)

    def _op_date_sub(self, e):
        return self._date_shift(e, -1)

    def _date_shift(self, e, sign: int):
        """packed datetime +/- INTERVAL n unit (ref: builtin_time date_add;
        semantics types/mytime.py datetime_add — Hinnant civil-from-days)."""
        d = self._eval(e.args[0])
        n = self._eval(e.args[1])
        unit = e.args[2].datum.val  # const string (planner contract)
        p = d.value
        micro = p & 0xFFFFFF
        rest = p >> 24
        hms = rest & ((1 << 17) - 1)
        ymd = rest >> 17
        day = ymd & 31
        ym = ymd >> 5
        y, m = ym // 13, ym % 13
        sec, minute, hour = hms & 63, (hms >> 6) & 63, hms >> 12
        nn = sign * n.value.astype(jnp.int64)
        from ..types.mytime import _UNIT_SECONDS, add_months

        if unit in _UNIT_SECONDS:
            total = _days_from_ymd(y, m, day) * 86400 + hour * 3600 + minute * 60 + sec + nn * _UNIT_SECONDS[unit]
            days, secs = total // 86400, total % 86400
            y, m, day = _ymd_from_days(days)
            hour, minute, sec = secs // 3600, (secs // 60) % 60, secs % 60
        elif unit in ("month", "quarter", "year"):
            months = nn * {"month": 1, "quarter": 3, "year": 12}[unit]
            y, m, day = add_months(y, m, day, months)
        else:
            raise NotImplementedError(f"interval unit {unit!r}")
        packed = (((y * 13 + m) << 5 | day) << 17 | (hour << 12 | minute << 6 | sec)) << 24 | micro
        return CompVal(packed, d.null | n.null, e.ft)

    def _op_datediff(self, e):
        a, b = self._eval(e.args[0]), self._eval(e.args[1])

        def days_of(v):
            ymd = v.value >> 41
            day = ymd & 31
            ym = ymd >> 5
            return _days_from_ymd(ym // 13, ym % 13, day)

        return CompVal(days_of(a) - days_of(b), a.null | b.null, e.ft)

    # -- time extraction (packed layout, types/mytime.py) ---------------------
    def _time_parts(self, a: CompVal):
        packed = a.value
        ymd = packed >> 41
        ym = ymd >> 5
        return packed, ymd, ym

    def _op_year(self, e):
        a = self._eval(e.args[0])
        _, _, ym = self._time_parts(a)
        return CompVal((ym // 13).astype(jnp.int64), a.null, e.ft)

    def _op_month(self, e):
        a = self._eval(e.args[0])
        _, _, ym = self._time_parts(a)
        return CompVal((ym % 13).astype(jnp.int64), a.null, e.ft)

    def _op_day(self, e):
        a = self._eval(e.args[0])
        _, ymd, _ = self._time_parts(a)
        return CompVal((ymd & 31).astype(jnp.int64), a.null, e.ft)

    def _op_hour(self, e):
        a = self._eval(e.args[0])
        hms = (a.value >> 24) & ((1 << 17) - 1)
        return CompVal((hms >> 12).astype(jnp.int64), a.null, e.ft)

    def _op_minute(self, e):
        a = self._eval(e.args[0])
        hms = (a.value >> 24) & ((1 << 17) - 1)
        return CompVal(((hms >> 6) & 63).astype(jnp.int64), a.null, e.ft)

    def _op_second(self, e):
        a = self._eval(e.args[0])
        hms = (a.value >> 24) & ((1 << 17) - 1)
        return CompVal((hms & 63).astype(jnp.int64), a.null, e.ft)

    def _op_to_days(self, e):
        """Days since year 0 (MySQL TO_DAYS) via civil-day arithmetic."""
        a = self._eval(e.args[0])
        _, ymd, ym = self._time_parts(a)
        y = ym // 13
        m = ym % 13
        d = ymd & 31
        # days from year 0: MySQL calcDaynr (ref: pkg/types/mytime.go calcDaynr)
        delsum = 365 * y + 31 * (m - 1) + d
        adj = jnp.where(m <= 2, 0, (0.4 * m.astype(jnp.float64) + 2.3).astype(jnp.int64))
        delsum = jnp.where(m <= 2, delsum, delsum - adj)
        yy = jnp.where(m <= 2, y - 1, y)
        out = delsum + yy // 4 - yy // 100 + yy // 400
        return CompVal(out.astype(jnp.int64), a.null, e.ft)

    def _op_weekday(self, e):
        a = self._eval(e.args[0])
        days = self._op_to_days(ScalarFunc("to_days", (e.args[0],), e.ft))
        return CompVal((days.value + 5) % 7, a.null, e.ft)

    def _op_extract(self, e):
        unit = e.args[0]
        if not isinstance(unit, Const):
            raise NotImplementedError
        u = str(unit.datum.val).lower()
        sub = ScalarFunc(u, (e.args[1],), e.ft)
        return self._eval(sub)


@dataclass
class CompiledExpr:
    """A jit-compiled projection over an input schema."""

    fn: Callable
    out_fts: list[FieldType]


def compile_exprs(input_fts: list[FieldType], exprs: list[Expr]) -> CompiledExpr:
    comp = ExprCompiler(input_fts)

    @jax.jit
    def run(cols):
        vals = comp.run(exprs, cols)
        return [(v.value, v.null) for v in vals]

    return CompiledExpr(run, [e.ft for e in exprs])
