from .ir import Expr, ColumnRef, Const, ScalarFunc, col, const, func, lit
from .agg import AggDesc, AggMode
from .compile import compile_exprs, CompiledExpr, ExprCompiler, CompVal

__all__ = [
    "Expr",
    "ColumnRef",
    "Const",
    "ScalarFunc",
    "col",
    "const",
    "func",
    "lit",
    "AggDesc",
    "AggMode",
    "compile_exprs",
    "CompiledExpr",
    "ExprCompiler",
    "CompVal",
]
