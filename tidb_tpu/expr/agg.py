"""Aggregate function descriptors (ref: pkg/expression/aggregation).

An AggDesc mirrors `AggFuncDesc`: function name, argument expressions, mode.
Modes (ref: aggregation/aggregation.go AggFunctionMode):

  Complete  raw rows in  -> final value out
  Partial1  raw rows in  -> partial state out      (device, per region)
  Partial2  partials in  -> merged partial out     (psum over mesh / host)
  Final     partials in  -> final value out        (root merge)

Partial-state schemas (what crosses regions and what psum reduces):

  count      [count int64]                    merge: +
  sum        [sum  argclass]                  merge: +   (NULL if no rows)
  avg        [count int64, sum argclass]      merge: +,+ (ref: aggfuncs avg)
  min / max  [val argclass]                   merge: min/max with null drop
  first_row  [has int64, val argclass]        merge: first state with has>0
             (has distinguishes "region saw no rows" from "first row's
              value is NULL" — the value itself may legitimately be NULL)
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

from ..types import FieldType, TypeCode, new_longlong
from .ir import Expr

AGG_FUNCS = frozenset({
    "count", "sum", "avg", "min", "max", "first_row", "bit_and", "bit_or", "bit_xor",
    # moment-based: states [count, sum, sum_sq] are additive -> mesh-mergeable
    "stddev_pop", "stddev_samp", "var_pop", "var_samp",
    # host-only (varlen accumulation): planned at root, oracle-evaluated
    "group_concat",
})


class AggMode(enum.IntEnum):
    Complete = 0
    Partial1 = 1
    Partial2 = 2
    Final = 3


@dataclass(frozen=True)
class AggDesc:
    name: str
    args: tuple  # tuple[Expr, ...]
    mode: AggMode = AggMode.Complete
    distinct: bool = False
    ft: FieldType | None = None  # result type (final); inferred if None
    extra: str | None = None  # group_concat SEPARATOR

    def __post_init__(self):
        if self.name not in AGG_FUNCS:
            raise ValueError(f"unknown aggregate {self.name!r}")
        if self.ft is None:
            object.__setattr__(self, "ft", self.infer_ft())

    def infer_ft(self) -> FieldType:
        """Result FieldType (ref: aggregation type inference in planner)."""
        if self.name == "count":
            return new_longlong(notnull=True)
        # In merge modes (Final/Partial2) args are partial-state columns:
        # [count, sum] for avg, [sum] for sum — the value column is last.
        arg_ft = self.args[-1].ft if self.args else new_longlong()
        if self.mode in (AggMode.Final, AggMode.Partial2):
            if self.name == "sum":
                return arg_ft.clone()
            if self.name == "avg":
                if arg_ft.eval_type() == "real":
                    return FieldType(TypeCode.Double)
                return FieldType(
                    TypeCode.NewDecimal,
                    flen=(arg_ft.flen or 20) + 4,
                    decimal=min(max(arg_ft.decimal, 0) + 4, 30),
                )
        if self.name == "first_row" and self.mode in (AggMode.Final, AggMode.Partial2) and len(self.args) > 1:
            # merge-mode first_row args are the [has, value] state columns;
            # the result type is the value column's, not the has flag's
            return self.args[-1].ft.clone()
        arg_ft = self.args[0].ft if self.args else new_longlong()
        if self.name in ("min", "max", "first_row"):
            return arg_ft.clone()
        if self.name in ("bit_and", "bit_or", "bit_xor"):
            return new_longlong(unsigned=True)
        if self.name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            return FieldType(TypeCode.Double)  # always DOUBLE (MySQL)
        if self.name == "group_concat":
            from ..types import new_varchar

            return new_varchar(1024)
        et = arg_ft.eval_type()
        if self.name == "sum":
            if et == "real":
                return FieldType(TypeCode.Double)
            # SUM over int/decimal returns DECIMAL (MySQL)
            return FieldType(TypeCode.NewDecimal, flen=arg_ft.flen + 10, decimal=max(arg_ft.decimal, 0))
        if self.name == "avg":
            if et == "real":
                return FieldType(TypeCode.Double)
            # AVG scale = arg scale + 4 (div frac increment)
            return FieldType(TypeCode.NewDecimal, flen=arg_ft.flen + 4, decimal=min(max(arg_ft.decimal, 0) + 4, 30))
        raise AssertionError(self.name)

    def partial_fts(self) -> list[FieldType]:
        """Schema of this aggregate's partial state columns."""
        if self.mode in (AggMode.Final, AggMode.Partial2) and self.args:
            # args already ARE the state columns
            return [a.ft.clone() for a in self.args]
        if self.name == "count":
            return [new_longlong(notnull=True)]
        arg_ft = self.args[0].ft
        et = arg_ft.eval_type()
        if self.name == "sum":
            return [self._sum_ft(arg_ft)]
        if self.name == "avg":
            return [new_longlong(notnull=True), self._sum_ft(arg_ft)]
        if self.name in ("min", "max"):
            return [arg_ft.clone()]
        if self.name == "first_row":
            return [new_longlong(notnull=True), arg_ft.clone()]
        if self.name in ("stddev_pop", "stddev_samp", "var_pop", "var_samp"):
            # additive moment states (ref: aggfuncs varPop partial results)
            return [new_longlong(notnull=True), FieldType(TypeCode.Double), FieldType(TypeCode.Double)]
        if self.name == "group_concat":
            return [self.infer_ft() if self.ft is None else self.ft.clone()]
        return [new_longlong(unsigned=True)]

    @staticmethod
    def _sum_ft(arg_ft: FieldType) -> FieldType:
        if arg_ft.eval_type() == "real":
            return FieldType(TypeCode.Double)
        return FieldType(TypeCode.NewDecimal, flen=(arg_ft.flen or 20) + 10, decimal=max(arg_ft.decimal, 0))

    def fingerprint(self) -> tuple:
        return (
            "agg",
            self.name,
            int(self.mode),
            self.distinct,
            self.extra,
            self.ft.tp,
            self.ft.decimal,
        ) + tuple(a.fingerprint() for a in self.args)
