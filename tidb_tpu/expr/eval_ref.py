"""Row-at-a-time reference evaluator — the parity oracle.

Re-expresses the semantics of the reference's naive coprocessor executors
(ref: unistore/cophandler/mpp_exec.go, pkg/expression builtin row Eval*) in
host Python over Datums. Every device kernel is cross-checked against this
(SURVEY.md §4: "bit-parity harness = run the same DAG through the Go-semantics
reference executor and the TPU kernels and diff chunks").

Slow by design; never on the hot path.
"""

from __future__ import annotations

import re

from ..types import Datum, DatumKind, FieldType, MyDecimal, MyTime, DIV_FRAC_INCR
from .ir import ColumnRef, Const, Expr, ScalarFunc

# MySQL string->number takes the longest valid numeric prefix
# (ref: pkg/types/convert.go getValidFloatPrefix)
_NUM_PREFIX = re.compile(r"^\s*[+-]?(\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?")


# host builtins that consume their string arguments as BYTES (encoded in
# the argument's column charset); everything else gets character semantics
_BYTE_SEMANTICS_OPS = frozenset({
    "md5", "sha", "sha1", "sha2", "password", "crc32", "compress",
    "uncompress", "uncompressed_length", "to_base64", "aes_encrypt",
    "aes_decrypt", "bit_length",
})

# character-unit builtins where a BINARY operand first converts into the
# string operand's charset (then character semantics apply; ref:
# builtin_string.go convertString on mixed binary/str args)
_BIN_TO_CHAR_OPS = frozenset({
    "instr", "position", "locate", "insert", "lpad", "rpad", "elt",
    "find_in_set", "field", "concat_ws",
})

_CHARSET_CODEC = {"gbk": "gbk", "gb2312": "gb2312", "gb18030": "gb18030",
                  "latin1": "latin-1", "ascii": "ascii", "utf8": "utf-8",
                  "utf8mb4": "utf-8", "big5": "big5"}


def charset_bytes(v, ft) -> bytes:
    """Value -> the bytes MySQL's byte-semantics functions (LENGTH, HEX,
    ASCII, OCTET_LENGTH) see: the column's declared charset encoding, with
    BINARY(n) zero-padding to the declared width (ref:
    pkg/expression/builtin_string.go Length over the stored bytes)."""
    if isinstance(v, (bytes, bytearray)):
        b = bytes(v)
    else:
        codec = _CHARSET_CODEC.get(getattr(ft, "charset", "") or "", "utf-8")
        b = str(v).encode(codec, "replace")
    return b


def _ascii_upper(s: str) -> str:
    """ASCII-only case fold (the general_ci subset every engine path uses)."""
    return "".join(chr(ord(c) - 32) if "a" <= c <= "z" else c for c in s)


def _ascii_lower(s: str) -> str:
    return "".join(chr(ord(c) + 32) if "A" <= c <= "Z" else c for c in s)


def str_prefix_f64(s) -> float:
    import math
    import sys as _sys

    if isinstance(s, (bytes, bytearray)):
        s = bytes(s).decode("utf-8", "replace")
    m = _NUM_PREFIX.match(s)
    v = float(m.group(0)) if m else 0.0
    if math.isinf(v):  # MySQL clamps to +/-DBL_MAX (convert.go StrToFloat)
        v = math.copysign(_sys.float_info.max, v)
    return v


def _num(d: Datum):
    return d.val


def _as_decimal(d: Datum) -> MyDecimal:
    if d.kind == DatumKind.MysqlDecimal:
        return d.val
    if d.kind in (DatumKind.Int64, DatumKind.Uint64):
        return MyDecimal(d.val, 0)
    if d.kind in (DatumKind.Float64, DatumKind.Float32):
        return MyDecimal(d.val)
    raise TypeError(f"cannot coerce {d} to decimal")


def _as_float(d: Datum) -> float:
    if d.kind == DatumKind.MysqlDecimal:
        return d.val.to_float()
    return float(d.val)


def _class2(a: Datum, b: Datum) -> str:
    ks = {a.kind, b.kind}
    if DatumKind.Float64 in ks or DatumKind.Float32 in ks:
        return "real"
    if DatumKind.MysqlDecimal in ks:
        return "decimal"
    if ks <= {DatumKind.String, DatumKind.Bytes}:
        return "string"
    return "int"


_JNULL = None  # python None doubles as JSON null (SQL NULL is Datum.NULL)


def _truth(d: Datum) -> bool | None:
    if d.is_null():
        return None
    if d.kind in (DatumKind.String, DatumKind.Bytes):
        return str_prefix_f64(d.val) != 0
    if d.kind == DatumKind.MysqlDecimal:
        return d.val.d != 0
    if d.kind == DatumKind.MysqlTime:
        return d.val.packed != 0
    return d.val != 0


def compare(a: Datum, b: Datum, ci: bool = False, collation=None) -> int | None:
    """3-way semantic compare; None if either side NULL. ci compares by
    collation WEIGHT BYTES (full Unicode, types/collate.py) — general_ci
    unless a specific collation is given."""
    if a.is_null() or b.is_null():
        return None
    cls = _class2(a, b)
    if cls == "string":
        if ci or collation is not None:
            from ..types.collate import weight_bytes
            from ..types.field_type import Collation

            coll = collation or Collation.Utf8MB4GeneralCI
            av = weight_bytes(a.val, coll)
            bv = weight_bytes(b.val, coll)
            return (av > bv) - (av < bv)
        av = a.val.encode() if isinstance(a.val, str) else bytes(a.val)
        bv = b.val.encode() if isinstance(b.val, str) else bytes(b.val)
        return (av > bv) - (av < bv)
    if cls == "real":
        av, bv = _as_float(a), _as_float(b)
        return (av > bv) - (av < bv)
    if cls == "decimal":
        av, bv = _as_decimal(a), _as_decimal(b)
        return (av.d > bv.d) - (av.d < bv.d)
    if a.kind == DatumKind.MysqlTime or b.kind == DatumKind.MysqlTime:
        av = a.val.packed if isinstance(a.val, MyTime) else a.val
        bv = b.val.packed if isinstance(b.val, MyTime) else b.val
        return (av > bv) - (av < bv)
    if a.kind in (DatumKind.MysqlEnum, DatumKind.MysqlSet) or b.kind in (DatumKind.MysqlEnum, DatumKind.MysqlSet):
        ek = (DatumKind.MysqlEnum, DatumKind.MysqlSet)
        if a.kind in ek and b.kind in ek:
            av, bv = int(a.val), int(b.val)  # member number (ref: types/enum.go)
        elif (b if a.kind in ek else a).kind in (DatumKind.String, DatumKind.Bytes):
            # enum vs string compares by NAME (ref: enum.go ConvertToString)
            av, bv = str(a.val), str(b.val)
            if ci:
                av, bv = av.upper(), bv.upper()
            return (av > bv) - (av < bv)
        else:
            av, bv = int(a.val), int(b.val)
        return (av > bv) - (av < bv)
    if a.kind == DatumKind.MysqlJSON or b.kind == DatumKind.MysqlJSON:
        # JSON equality is exact after coercing the other side to a JSON
        # scalar; ordering approximates MySQL's type-precedence rules with
        # text order (documented divergence)
        from ..types import json_binary as jb

        ja = jb.decode(a.val) if a.kind == DatumKind.MysqlJSON else RefEvaluator._jscalar(a)
        jv = jb.decode(b.val) if b.kind == DatumKind.MysqlJSON else RefEvaluator._jscalar(b)
        if jb._eq(ja, jv):
            return 0
        at, bt = jb.to_text(ja), jb.to_text(jv)
        return (at > bt) - (at < bt)
    av, bv = a.val, b.val  # python ints compare exactly regardless of sign
    return (av > bv) - (av < bv)


class RefEvaluator:
    """Evaluate an Expr over one row of Datums."""

    def eval(self, e: Expr, row: list[Datum]) -> Datum:
        if isinstance(e, ColumnRef):
            return row[e.index]
        if isinstance(e, Const):
            return e.datum
        assert isinstance(e, ScalarFunc)
        method = getattr(self, f"_op_{e.op}", None)
        if method is None:
            from ..expr.ir import EXTENSION_OPS

            if e.op in EXTENSION_OPS:
                from ..sql.extension import EXTENSIONS

                ds = self._args(e, row)
                if e.op in _BIN_TO_CHAR_OPS:
                    csl = [(getattr(ae.ft, "charset", "") or "").lower()
                           for ae in e.args]
                    target = next((c for c in csl if c not in ("", "binary")),
                                  "utf8mb4")
                    codec = _CHARSET_CODEC.get(target, "utf-8")
                    ds = [
                        Datum.string(bytes(d.val).decode(codec, "replace"))
                        if (not d.is_null()
                            and isinstance(d.val, (bytes, bytearray)))
                        else d
                        for d in ds
                    ]
                if e.op in _BYTE_SEMANTICS_OPS:
                    # byte-semantics parity: a gbk/latin1/binary argument
                    # reaches these host builtins as its COLUMN CHARSET
                    # bytes, not re-encoded utf-8 (ref:
                    # builtin_encryption.go: args convert via arg charset).
                    # Character-unit builtins (INSTR, ELT, LPAD...) keep
                    # their str arguments — byte offsets would be wrong.
                    ds = [
                        Datum.bytes_(charset_bytes(d.val, ae.ft))
                        if (not d.is_null() and isinstance(d.val, str)
                            and (getattr(ae.ft, "charset", "") or "").lower()
                            not in ("", "utf8", "utf8mb4"))
                        else d
                        for d, ae in zip(ds, e.args)
                    ]
                return EXTENSIONS.call(e.op, ds)
            raise NotImplementedError(f"no reference evaluator for {e.op!r}")
        return method(e, row)

    # -- helpers -------------------------------------------------------------
    def _args(self, e, row):
        return [self.eval(a, row) for a in e.args]

    @staticmethod
    def _jval(d: Datum):
        """Datum -> python JSON value (None return means SQL NULL input)."""
        from ..types import json_binary as jb

        if d.is_null():
            return _JNULL
        if d.kind == DatumKind.MysqlJSON:
            return jb.decode(d.val)
        if d.kind in (DatumKind.String, DatumKind.Bytes):
            txt = d.val if isinstance(d.val, str) else bytes(d.val).decode("utf-8", "surrogateescape")
            return jb.parse_text(txt)
        if d.kind in (DatumKind.Int64, DatumKind.Uint64):
            return int(d.val)
        if d.kind in (DatumKind.Float32, DatumKind.Float64):
            return float(d.val)
        if d.kind == DatumKind.MysqlDecimal:
            return float(d.val.to_float())
        raise NotImplementedError(f"cannot treat {d.kind.name} as JSON")

    @staticmethod
    def _jscalar(d: Datum):
        """SQL value -> JSON SCALAR (strings stay strings — MySQL treats
        string args of JSON_ARRAY/JSON_OBJECT/MEMBER OF as values, not
        JSON text to parse)."""
        from ..types import json_binary as jb

        if d.kind == DatumKind.MysqlJSON:
            return jb.decode(d.val)
        if d.kind in (DatumKind.String, DatumKind.Bytes):
            return d.val if isinstance(d.val, str) else bytes(d.val).decode("utf-8", "surrogateescape")
        if d.kind in (DatumKind.Int64, DatumKind.Uint64):
            return int(d.val)
        if d.kind in (DatumKind.Float32, DatumKind.Float64):
            return float(d.val)
        if d.kind == DatumKind.MysqlDecimal:
            return float(d.val.to_float())
        return str(d.val)

    @staticmethod
    def _jdatum(v) -> Datum:
        from ..types import json_binary as jb

        return Datum.json(jb.encode(v))

    # -- JSON (ref: pkg/expression/builtin_json_vec.go; semantics
    # pkg/types/json_binary_functions.go) --------------------------------
    def _op_json_extract(self, e, row):
        args = self._args(e, row)
        if any(a.is_null() for a in args):
            return Datum.NULL
        doc = self._jval(args[0])
        paths = [str(a.val) for a in args[1:]]
        from ..types import json_binary as jb

        found, v = jb.extract(doc, paths)
        return self._jdatum(v) if found else Datum.NULL

    def _op_json_unquote(self, e, row):
        a = self._args(e, row)[0]
        if a.is_null():
            return Datum.NULL
        from ..types import json_binary as jb

        if a.kind in (DatumKind.String, DatumKind.Bytes):
            # MySQL only parses/unquotes double-quoted JSON strings; any
            # other plain string passes through unchanged
            txt = a.val if isinstance(a.val, str) else bytes(a.val).decode("utf-8", "surrogateescape")
            if txt.startswith('"') and txt.endswith('"'):
                try:
                    v = jb.parse_text(txt)
                    if isinstance(v, str):
                        return Datum.string(v)
                except ValueError:
                    pass
            return Datum.string(txt)
        v = self._jval(a)
        if isinstance(v, str):
            return Datum.string(v)
        return Datum.string(jb.to_text(v))

    def _op_json_type(self, e, row):
        a = self._args(e, row)[0]
        if a.is_null():
            return Datum.NULL
        from ..types import json_binary as jb

        return Datum.string(jb.json_type_name(self._jval(a)))

    def _op_json_valid(self, e, row):
        a = self._args(e, row)[0]
        if a.is_null():
            return Datum.NULL
        if a.kind == DatumKind.MysqlJSON:
            return Datum.i64(1)
        if a.kind not in (DatumKind.String, DatumKind.Bytes):
            return Datum.i64(0)
        try:
            self._jval(a)
            return Datum.i64(1)
        except ValueError:
            return Datum.i64(0)

    def _op_json_length(self, e, row):
        args = self._args(e, row)
        if any(a.is_null() for a in args):
            return Datum.NULL
        v = self._jval(args[0])
        if len(args) > 1:
            from ..types import json_binary as jb

            found, v = jb.extract(v, [str(args[1].val)])
            if not found:
                return Datum.NULL
        if isinstance(v, (list, dict)):
            return Datum.i64(len(v))
        return Datum.i64(1)

    def _op_json_keys(self, e, row):
        args = self._args(e, row)
        if any(a.is_null() for a in args):
            return Datum.NULL
        v = self._jval(args[0])
        if len(args) > 1:
            from ..types import json_binary as jb

            found, v = jb.extract(v, [str(args[1].val)])
            if not found:
                return Datum.NULL
        if not isinstance(v, dict):
            return Datum.NULL
        return self._jdatum(list(v.keys()))

    def _op_json_contains(self, e, row):
        args = self._args(e, row)
        if any(a.is_null() for a in args):
            return Datum.NULL
        from ..types import json_binary as jb

        return Datum.i64(1 if jb.contains(self._jval(args[0]), self._jval(args[1])) else 0)

    def _op_json_member_of(self, e, row):
        args = self._args(e, row)
        if any(a.is_null() for a in args):
            return Datum.NULL
        from ..types import json_binary as jb

        target, arr = self._jscalar(args[0]), self._jval(args[1])
        if isinstance(arr, list):
            return Datum.i64(1 if any(jb._eq(x, target) for x in arr) else 0)
        return Datum.i64(1 if jb._eq(arr, target) else 0)

    def _op_json_array(self, e, row):
        return self._jdatum([None if a.is_null() else self._jscalar(a) for a in self._args(e, row)])

    def _op_json_object(self, e, row):
        args = self._args(e, row)
        if len(args) % 2 != 0:
            raise ValueError(
                "Incorrect parameter count in the call to native function 'json_object'"
            )
        obj = {}
        for i in range(0, len(args), 2):
            k = args[i]
            if k.is_null():
                raise ValueError("JSON documents may not contain NULL member names")
            obj[str(k.val)] = None if args[i + 1].is_null() else self._jscalar(args[i + 1])
        return self._jdatum(obj)

    def _op_json_quote(self, e, row):
        a = self._args(e, row)[0]
        if a.is_null():
            return Datum.NULL
        import json as _pyjson

        return Datum.string(_pyjson.dumps(str(a.val), ensure_ascii=False))

    # -- regexp (ref: pkg/expression/builtin_regexp_vec.go) --------------
    def _regexp_match(self, e, row, with_match_type: bool):
        import re as _re

        args = self._args(e, row)
        if any(a.is_null() for a in args[:2]):
            return None
        def _txt(d):
            if isinstance(d.val, str):
                return d.val
            if isinstance(d.val, (bytes, bytearray, memoryview)):
                return bytes(d.val).decode("utf-8", "surrogateescape")
            return str(d.val)  # enum/set render as member names

        subject, pattern = _txt(args[0]), _txt(args[1])
        flags = 0
        ci = bool(e.args[0].ft.is_ci() or e.args[1].ft.is_ci())
        if with_match_type and len(args) > 2 and not args[2].is_null():
            mt = str(args[2].val)
            if "c" in mt:
                ci = False
            if "i" in mt:
                ci = True
            if "n" in mt:
                flags |= _re.DOTALL
            if "m" in mt:
                flags |= _re.MULTILINE
        if ci:
            flags |= _re.IGNORECASE
        return _re.search(pattern, subject, flags) is not None

    def _op_regexp(self, e, row):
        m = self._regexp_match(e, row, False)
        return Datum.NULL if m is None else Datum.i64(1 if m else 0)

    def _op_regexp_like(self, e, row):
        m = self._regexp_match(e, row, True)
        return Datum.NULL if m is None else Datum.i64(1 if m else 0)

    def _result_num(self, v, ft: FieldType) -> Datum:
        if v is None:
            return Datum.NULL
        if ft.eval_type() == "decimal":
            return Datum.dec(v if isinstance(v, MyDecimal) else MyDecimal(v, max(ft.decimal, 0)))
        if ft.eval_type() == "real":
            return Datum.f64(float(v))
        if ft.is_unsigned():
            return Datum.u64(int(v))
        return Datum.i64(int(v))

    def _arith(self, e, row, int_fn, real_fn, dec_fn):
        a, b = self._args(e, row)
        if a.is_null() or b.is_null():
            return Datum.NULL
        cls = _class2(a, b)
        if cls == "real":
            return self._result_num(real_fn(_as_float(a), _as_float(b)), e.ft)
        if cls == "decimal":
            return self._result_num(dec_fn(_as_decimal(a), _as_decimal(b)), e.ft)
        return self._result_num(int_fn(a.val, b.val), e.ft)

    # -- arithmetic ----------------------------------------------------------
    def _op_plus(self, e, row):
        return self._arith(e, row, lambda a, b: a + b, lambda a, b: a + b, lambda a, b: a + b)

    def _op_minus(self, e, row):
        return self._arith(e, row, lambda a, b: a - b, lambda a, b: a - b, lambda a, b: a - b)

    def _op_mul(self, e, row):
        return self._arith(e, row, lambda a, b: a * b, lambda a, b: a * b, lambda a, b: a * b)

    def _op_div(self, e, row):
        a, b = self._args(e, row)
        if a.is_null() or b.is_null():
            return Datum.NULL
        if _class2(a, b) == "real":
            bf = _as_float(b)
            if bf == 0.0:
                return Datum.NULL
            return Datum.f64(_as_float(a) / bf)
        q = _as_decimal(a).div(_as_decimal(b))
        if q is None:
            return Datum.NULL
        return Datum.dec(q.round(max(e.ft.decimal, 0)))

    def _op_intdiv(self, e, row):
        a, b = self._args(e, row)
        if a.is_null() or b.is_null():
            return Datum.NULL
        if _class2(a, b) in ("decimal", "real"):
            ad, bd = _as_decimal(a), _as_decimal(b)
            if bd.d == 0:
                return Datum.NULL
            q = ad.d / bd.d
            return self._result_num(int(q), e.ft)
        if b.val == 0:
            return Datum.NULL
        q = abs(a.val) // abs(b.val)
        return self._result_num(-q if (a.val < 0) != (b.val < 0) else q, e.ft)

    def _op_mod(self, e, row):
        a, b = self._args(e, row)
        if a.is_null() or b.is_null():
            return Datum.NULL
        if _class2(a, b) == "real":
            bf = _as_float(b)
            if bf == 0.0:
                return Datum.NULL
            import math

            return Datum.f64(math.fmod(_as_float(a), bf))
        if _class2(a, b) == "decimal":
            ad, bd = _as_decimal(a), _as_decimal(b)
            if bd.d == 0:
                return Datum.NULL
            s = max(ad.scale, bd.scale)
            r = abs(ad.d) % abs(bd.d)
            return Datum.dec(MyDecimal(-r if ad.d < 0 else r, s))
        if b.val == 0:
            return Datum.NULL
        r = abs(a.val) % abs(b.val)
        return self._result_num(-r if a.val < 0 else r, e.ft)

    def _op_unaryminus(self, e, row):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        if a.kind == DatumKind.MysqlDecimal:
            return Datum.dec(-a.val)
        return self._result_num(-a.val, e.ft)

    def _op_abs(self, e, row):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        if a.kind == DatumKind.MysqlDecimal:
            return Datum.dec(MyDecimal(abs(a.val.d), a.val.scale))
        return self._result_num(abs(a.val), e.ft)

    # -- comparison ----------------------------------------------------------
    @staticmethod
    def _ci(e) -> bool:
        return any(a.ft.is_string() and a.ft.is_ci() for a in e.args)

    @staticmethod
    def _coll(e):
        for a in e.args:
            if a.ft.is_string() and a.ft.is_ci():
                return a.ft.collate
        return None

    def _cmp_op(self, e, row, pred):
        a, b = self._args(e, row)
        a, b = self._bin_coerce(e, a, b)
        c = compare(a, b, ci=self._ci(e), collation=self._coll(e))
        if c is None:
            return Datum.NULL
        return Datum.i64(1 if pred(c) else 0)

    @staticmethod
    def _bin_coerce(e, a, b):
        """Binary-vs-string comparison compares the string side's COLUMN
        CHARSET bytes (ref: pkg/expression/builtin_compare.go with a binary
        collation operand; hex literals are VARBINARY)."""
        if len(e.args) < 2:
            return a, b
        ka = isinstance(a.val, (bytes, bytearray)) and not a.is_null()
        kb = isinstance(b.val, (bytes, bytearray)) and not b.is_null()
        if ka == kb:
            return a, b
        if ka and isinstance(b.val, str):
            b = Datum.bytes_(charset_bytes(b.val, e.args[1].ft))
        elif kb and isinstance(a.val, str):
            a = Datum.bytes_(charset_bytes(a.val, e.args[0].ft))
        return a, b

    def _op_eq(self, e, row):
        return self._cmp_op(e, row, lambda c: c == 0)

    def _op_ne(self, e, row):
        return self._cmp_op(e, row, lambda c: c != 0)

    def _op_lt(self, e, row):
        return self._cmp_op(e, row, lambda c: c < 0)

    def _op_le(self, e, row):
        return self._cmp_op(e, row, lambda c: c <= 0)

    def _op_gt(self, e, row):
        return self._cmp_op(e, row, lambda c: c > 0)

    def _op_ge(self, e, row):
        return self._cmp_op(e, row, lambda c: c >= 0)

    def _op_nulleq(self, e, row):
        a, b = self._args(e, row)
        if a.is_null() and b.is_null():
            return Datum.i64(1)
        c = compare(a, b)
        return Datum.i64(1 if c == 0 else 0)

    def _op_in(self, e, row):
        a = self.eval(e.args[0], row)
        if a.is_null():
            return Datum.NULL
        saw_null = False
        for arg in e.args[1:]:
            b = self.eval(arg, row)
            c = compare(a, b, ci=self._ci(e), collation=self._coll(e))
            if c is None:
                saw_null = True
            elif c == 0:
                return Datum.i64(1)
        return Datum.NULL if saw_null else Datum.i64(0)

    def _op_between(self, e, row):
        a, lo, hi = self._args(e, row)
        ci = self._ci(e)
        coll = self._coll(e)
        c1, c2 = compare(a, lo, ci=ci, collation=coll), compare(a, hi, ci=ci, collation=coll)
        if c1 is None or c2 is None:
            return Datum.NULL
        return Datum.i64(1 if c1 >= 0 and c2 <= 0 else 0)

    # -- logical -------------------------------------------------------------
    def _op_and(self, e, row):
        a, b = self._args(e, row)
        ta, tb = _truth(a), _truth(b)
        if ta is False or tb is False:
            return Datum.i64(0)
        if ta is None or tb is None:
            return Datum.NULL
        return Datum.i64(1)

    def _op_or(self, e, row):
        a, b = self._args(e, row)
        ta, tb = _truth(a), _truth(b)
        if ta is True or tb is True:
            return Datum.i64(1)
        if ta is None or tb is None:
            return Datum.NULL
        return Datum.i64(0)

    def _op_not(self, e, row):
        (a,) = self._args(e, row)
        t = _truth(a)
        if t is None:
            return Datum.NULL
        return Datum.i64(0 if t else 1)

    def _op_xor(self, e, row):
        a, b = self._args(e, row)
        ta, tb = _truth(a), _truth(b)
        if ta is None or tb is None:
            return Datum.NULL
        return Datum.i64(1 if ta != tb else 0)

    # -- null / control ------------------------------------------------------
    def _op_isnull(self, e, row):
        (a,) = self._args(e, row)
        return Datum.i64(1 if a.is_null() else 0)

    def _op_ifnull(self, e, row):
        a, b = self._args(e, row)
        return b if a.is_null() else a

    def _op_if(self, e, row):
        c, a, b = self._args(e, row)
        return a if _truth(c) else b

    def _op_case(self, e, row):
        args = e.args
        i = 0
        while i + 1 < len(args):
            if _truth(self.eval(args[i], row)):
                return self.eval(args[i + 1], row)
            i += 2
        if i < len(args):
            return self.eval(args[i], row)
        return Datum.NULL

    def _op_coalesce(self, e, row):
        for a in e.args:
            v = self.eval(a, row)
            if not v.is_null():
                return v
        return Datum.NULL

    # -- cast ----------------------------------------------------------------
    def _op_cast(self, e, row):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        dst = e.ft.eval_type()
        if dst == "real":
            return Datum.f64(_as_float(a))
        if dst == "decimal":
            return Datum.dec(_as_decimal(a).round(max(e.ft.decimal, 0)))
        if dst == "int":
            if a.kind in (DatumKind.Float64, DatumKind.Float32):
                import math

                v = a.val
                return self._result_num(int(math.floor(v + 0.5)) if v >= 0 else int(math.ceil(v - 0.5)), e.ft)
            if a.kind == DatumKind.MysqlDecimal:
                return self._result_num(a.val.to_int(), e.ft)
            return self._result_num(a.val, e.ft)
        if dst == "string":
            if a.kind in (DatumKind.String, DatumKind.Bytes):
                return a
            return Datum.string(str(a.val))
        if dst == "time":
            from ..types import TypeCode as _TC

            if a.kind in (DatumKind.String, DatumKind.Bytes):
                # CAST('...' AS DATETIME/DATE) (ref: builtin_cast.go
                # castStringAsTime -> types.ParseTime); bare time-of-day
                # strings parse at the zero date ('10:30:00' -> hour 10)
                s = self._sval(a).strip()
                try:
                    t = MyTime.parse(s, max(e.ft.decimal, 0))
                except (ValueError, TypeError):
                    try:
                        t = MyTime.parse("0000-00-00 " + s, max(e.ft.decimal, 0))
                    except (ValueError, TypeError):
                        return Datum.NULL
                a = Datum.time(t)
            if e.ft.tp == _TC.Date and isinstance(a.val, MyTime):
                from ..types.mytime import unpack_datetime

                y, m, d2, *_ = unpack_datetime(a.val.packed)
                return Datum.time(MyTime.from_ymd(y, m, d2))
            return a
        raise NotImplementedError(f"ref cast to {dst}")

    # -- math ----------------------------------------------------------------
    def _op_ceil(self, e, row):
        import math

        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        if a.kind == DatumKind.MysqlDecimal:
            return self._result_num(int(math.ceil(a.val.d)), e.ft)
        if a.kind == DatumKind.Float64:
            return Datum.f64(math.ceil(a.val))
        return a

    def _op_floor(self, e, row):
        import math

        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        if a.kind == DatumKind.MysqlDecimal:
            return self._result_num(int(math.floor(a.val.d)), e.ft)
        if a.kind == DatumKind.Float64:
            return Datum.f64(math.floor(a.val))
        return a

    def _op_round(self, e, row):
        a = self.eval(e.args[0], row)
        nd = 0
        if len(e.args) > 1:
            d = self.eval(e.args[1], row)
            if d.is_null():
                return Datum.NULL
            nd = int(d.val)
        if a.is_null():
            return Datum.NULL
        if a.kind == DatumKind.MysqlDecimal:
            tgt = min(max(nd, 0), a.val.scale)
            return Datum.dec(a.val.round(tgt).round(max(e.ft.decimal, 0)))
        if a.kind == DatumKind.Float64:
            import math

            p = 10.0 ** nd
            v = a.val * p
            out = math.floor(v + 0.5) if v >= 0 else math.ceil(v - 0.5)
            return Datum.f64(out / p)
        if nd >= 0:
            return a
        p = 10 ** (-nd)
        v = a.val
        q = (abs(v) * 2 + p) // (2 * p) * p
        return self._result_num(-q if v < 0 else q, e.ft)

    def _op_sqrt(self, e, row):
        import math

        (a,) = self._args(e, row)
        if a.is_null() or _as_float(a) < 0:
            return Datum.NULL
        return Datum.f64(math.sqrt(_as_float(a)))

    def _op_exp(self, e, row):
        import math

        (a,) = self._args(e, row)
        return Datum.NULL if a.is_null() else Datum.f64(math.exp(_as_float(a)))

    def _op_ln(self, e, row):
        import math

        (a,) = self._args(e, row)
        if a.is_null() or _as_float(a) <= 0:
            return Datum.NULL
        return Datum.f64(math.log(_as_float(a)))

    _op_log = _op_ln

    def _op_pow(self, e, row):
        a, b = self._args(e, row)
        if a.is_null() or b.is_null():
            return Datum.NULL
        return Datum.f64(_as_float(a) ** _as_float(b))

    def _op_sign(self, e, row):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        v = _as_float(a)
        return Datum.i64((v > 0) - (v < 0))

    # -- bit -----------------------------------------------------------------
    def _bits(self, e, row, fn):
        a, b = self._args(e, row)
        if a.is_null() or b.is_null():
            return Datum.NULL
        return Datum.u64(fn(a.val & 0xFFFFFFFFFFFFFFFF, b.val & 0xFFFFFFFFFFFFFFFF) & 0xFFFFFFFFFFFFFFFF)

    def _op_bitand(self, e, row):
        return self._bits(e, row, lambda a, b: a & b)

    def _op_bitor(self, e, row):
        return self._bits(e, row, lambda a, b: a | b)

    def _op_bitxor(self, e, row):
        return self._bits(e, row, lambda a, b: a ^ b)

    def _op_bitneg(self, e, row):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        return Datum.u64(~a.val & 0xFFFFFFFFFFFFFFFF)

    def _op_shiftleft(self, e, row):
        return self._bits(e, row, lambda a, b: 0 if b >= 64 else a << b)

    def _op_shiftright(self, e, row):
        return self._bits(e, row, lambda a, b: 0 if b >= 64 else a >> b)

    # -- string --------------------------------------------------------------
    def _op_length(self, e, row):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        return Datum.i64(len(charset_bytes(a.val, e.args[0].ft)))

    def _op_octet_length(self, e, row):
        return self._op_length(e, row)

    def _op_hex(self, e, row):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        if isinstance(a.val, (int,)) or a.kind in (DatumKind.Int64, DatumKind.Uint64):
            return Datum.string(format(int(a.val), "X"))
        return Datum.string(charset_bytes(a.val, e.args[0].ft).hex().upper())

    def _op_ascii(self, e, row):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        b = charset_bytes(a.val, e.args[0].ft)
        return Datum.i64(b[0] if b else 0)

    def _op_ord(self, e, row):
        # ORD: leading multi-byte character folded big-endian (MySQL docs)
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        b = charset_bytes(a.val, e.args[0].ft)
        if not b:
            return Datum.i64(0)
        s = a.val if isinstance(a.val, str) else None
        if s:
            cb = charset_bytes(s[0], e.args[0].ft)
            n = 0
            for x in cb:
                n = n * 256 + x
            return Datum.i64(n)
        return Datum.i64(b[0])

    def _op_strcmp(self, e, row):
        a, b = self._args(e, row)
        c = compare(a, b)
        return Datum.NULL if c is None else Datum.i64(c)

    def _op_like(self, e, row):
        import re

        a, p = self._args(e, row)
        if a.is_null() or p.is_null():
            return Datum.NULL
        if isinstance(p.val, (bytes, bytearray)) or isinstance(a.val, (bytes, bytearray)):
            # binary operand: LIKE matches over the string side's COLUMN
            # CHARSET bytes, latin1-lifted so the regex machinery stays 1:1
            # with byte positions (same coercion rule as _bin_coerce)
            a, p = self._bin_coerce(e, a, p)
            s = bytes(a.val).decode("latin1") if isinstance(a.val, (bytes, bytearray)) else a.val
            pat = bytes(p.val).decode("latin1") if isinstance(p.val, (bytes, bytearray)) else p.val
        else:
            s = a.val
            pat = p.val
        if self._ci(e):
            # the SAME per-collation fold weight_bytes uses — '=' and LIKE
            # must agree (types/collate.py fold_text)
            from ..types.collate import fold_text
            from ..types.field_type import Collation

            coll = self._coll(e) or Collation.Utf8MB4GeneralCI
            s, pat = fold_text(s, coll), fold_text(pat, coll)
        rx = re.escape(pat).replace(re.escape("%"), ".*").replace(re.escape("_"), ".")
        return Datum.i64(1 if re.fullmatch(rx, s, re.S) else 0)

    def _op_substr(self, e, row):
        args = self._args(e, row)
        a = args[0]
        if any(x.is_null() for x in args):
            return Datum.NULL
        s = a.val if isinstance(a.val, str) else a.val.decode("utf-8", "surrogateescape")
        pos = int(args[1].val)
        if pos > 0:
            start = pos - 1
        elif pos < 0:
            start = len(s) + pos
            if start < 0:  # MySQL: position before string start -> ''
                return Datum.string("")
        else:
            return Datum.string("")
        ln = int(args[2].val) if len(args) > 2 else None
        out = s[start : start + ln] if ln is not None else s[start:]
        return Datum.string(out)

    @staticmethod
    def _sval(d: Datum) -> str:
        v = d.val
        if isinstance(v, str):
            return v
        if isinstance(v, (bytes, bytearray)):
            return bytes(v).decode("utf-8", "surrogateescape")
        if isinstance(v, MyDecimal):
            return str(v)
        return str(v)

    def _op_convert_using(self, e, row):
        """CONVERT(expr USING cs) (ref: builtin_string.go builtinConvertSig):
        USING binary yields the source-charset bytes; otherwise the text
        round-trips through the target codec with '?' for unencodable."""
        a, csd = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        cs = str(csd.val).lower()
        if cs == "binary":
            return Datum.bytes_(charset_bytes(a.val, e.args[0].ft))
        codec = _CHARSET_CODEC.get(cs, "utf-8")
        if isinstance(a.val, (bytes, bytearray)):
            return Datum.string(bytes(a.val).decode(codec, "replace"))
        s = self._sval(a)
        return Datum.string(s.encode(codec, "replace").decode(codec, "replace"))

    def _op_concat(self, e, row):
        args = self._args(e, row)
        if any(a.is_null() for a in args):
            return Datum.NULL
        if any(isinstance(a.val, (bytes, bytearray)) for a in args):
            # a binary operand makes CONCAT binary: every piece contributes
            # its COLUMN-CHARSET bytes (ref: builtin_string.go concat with
            # binary collation propagation)
            return Datum.bytes_(b"".join(
                charset_bytes(a.val, ae.ft) for a, ae in zip(args, e.args)
            ))
        return Datum.string("".join(self._sval(a) for a in args))

    def _str1(self, e, row, fn):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        return Datum.string(fn(self._sval(a)))

    @staticmethod
    def _case_cs(e):
        return (getattr(e.args[0].ft, "charset", "") or "").lower()

    def _op_upper(self, e, row):
        # gbk-class charsets case-map ASCII only (ref:
        # pkg/util/charset/encoding_gbk.go ToUpper/ToLower special-casing)
        if self._case_cs(e) in ("gbk", "gb2312", "gb18030", "big5"):
            return self._str1(e, row, _ascii_upper)
        return self._str1(e, row, str.upper)

    def _op_lower(self, e, row):
        if self._case_cs(e) in ("gbk", "gb2312", "gb18030", "big5"):
            return self._str1(e, row, _ascii_lower)
        return self._str1(e, row, str.lower)

    def _op_trim(self, e, row):
        return self._str1(e, row, lambda s: s.strip(" "))

    def _op_ltrim(self, e, row):
        return self._str1(e, row, lambda s: s.lstrip(" "))

    def _op_rtrim(self, e, row):
        return self._str1(e, row, lambda s: s.rstrip(" "))

    def _op_replace(self, e, row):
        a, frm, to = self._args(e, row)
        if a.is_null() or frm.is_null() or to.is_null():
            return Datum.NULL
        f = self._sval(frm)
        if f == "":
            return Datum.string(self._sval(a))
        return Datum.string(self._sval(a).replace(f, self._sval(to)))

    # -- date arithmetic ------------------------------------------------------
    def _op_date_add(self, e, row):
        return self._date_shift(e, row, +1)

    def _op_date_sub(self, e, row):
        return self._date_shift(e, row, -1)

    def _date_shift(self, e, row, sign: int):
        from ..types.mytime import datetime_add

        d, n = self.eval(e.args[0], row), self.eval(e.args[1], row)
        unit = e.args[2].datum.val  # const string
        if d.is_null() or n.is_null():
            return Datum.NULL
        t = d.val if isinstance(d.val, MyTime) else MyTime(int(d.val))
        return Datum.time(MyTime(datetime_add(t.packed, sign * int(n.val), str(unit)), t.fsp))

    def _op_datediff(self, e, row):
        from ..types.mytime import days_from_civil

        a, b = self._args(e, row)
        if a.is_null() or b.is_null():
            return Datum.NULL
        ya, ma, da = self._time_parts(a)[:3]
        yb, mb, db = self._time_parts(b)[:3]
        return Datum.i64(days_from_civil(ya, ma, da) - days_from_civil(yb, mb, db))

    # -- time ----------------------------------------------------------------
    def _time_parts(self, a: Datum):
        t = a.val if isinstance(a.val, MyTime) else MyTime(int(a.val))
        return t.parts()

    def _tfield(self, e, row, idx):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        return Datum.i64(self._time_parts(a)[idx])

    def _op_year(self, e, row):
        return self._tfield(e, row, 0)

    def _op_month(self, e, row):
        return self._tfield(e, row, 1)

    def _op_day(self, e, row):
        return self._tfield(e, row, 2)

    def _op_hour(self, e, row):
        return self._tfield(e, row, 3)

    def _op_minute(self, e, row):
        return self._tfield(e, row, 4)

    def _op_second(self, e, row):
        return self._tfield(e, row, 5)

    def _op_to_days(self, e, row):
        (a,) = self._args(e, row)
        if a.is_null():
            return Datum.NULL
        y, m, d = self._time_parts(a)[:3]
        delsum = 365 * y + 31 * (m - 1) + d
        if m > 2:
            delsum -= int(0.4 * m + 2.3)
            yy = y
        else:
            yy = y - 1
        return Datum.i64(delsum + yy // 4 - yy // 100 + yy // 400)

    def _op_weekday(self, e, row):
        d = self._op_to_days(e, row)
        if d.is_null():
            return Datum.NULL
        return Datum.i64((d.val + 5) % 7)

    def _op_extract(self, e, row):
        unit = e.args[0]
        u = str(unit.datum.val).lower()
        from .ir import ScalarFunc as SF

        return self.eval(SF(u, (e.args[1],), e.ft), row)
