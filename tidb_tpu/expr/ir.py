"""Expression IR — the engine's analog of `tipb.Expr` trees.

The reference serializes planner expressions to protobuf (ref:
pkg/expression/expr_to_pb.go:37 ExpressionsToPBList) and rebuilds them on the
coprocessor side (ref: pkg/expression/distsql_builtin.go). Here the IR *is*
the wire/plan form: immutable, hashable nodes carrying a result FieldType, so
a whole DAG fingerprints to a cache key for compiled XLA programs
(SURVEY.md §7 layer 4).

Ops use generic names; the eval class of the *arguments* selects the concrete
semantics at compile time, mirroring how tipb ScalarFuncSig variants
(GTInt/GTReal/GTDecimal/...) are chosen by pkg/expression type inference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..types import Datum, FieldType

# Canonical op names understood by the compiler (compile.py OP table) and the
# reference evaluator (eval_ref.py). Mirrors the pushdown whitelist idea of
# infer_pushdown.go:160 — anything outside this set cannot be pushed to TPU.
SCALAR_OPS = frozenset(
    {
        # arithmetic
        "plus", "minus", "mul", "div", "intdiv", "mod", "unaryminus", "abs",
        # comparison
        "eq", "ne", "lt", "le", "gt", "ge", "nulleq", "in", "between",
        # logical
        "and", "or", "not", "xor",
        # JSON + regexp (host-only: distsql/root.py HOST_ONLY keeps them
        # at the root oracle; ref: builtin_json_vec.go, builtin_regexp_vec.go)
        "json_extract", "json_unquote", "json_type", "json_valid",
        "json_length", "json_keys", "json_contains", "json_member_of",
        "json_array", "json_object", "json_quote", "regexp", "regexp_like",
        "convert_using",
        # null handling / control
        "isnull", "ifnull", "if", "case", "coalesce",
        # casts (target class from result ft)
        "cast",
        # math
        "ceil", "floor", "round", "sqrt", "exp", "log", "ln", "pow", "sign",
        # string (device subset; packed-word ops)
        "like", "length", "strcmp", "substr",
        "concat", "upper", "lower", "trim", "ltrim", "rtrim", "replace",
        # date/time extraction from packed datetime
        "year", "month", "day", "hour", "minute", "second", "weekday", "to_days", "extract",
        # date arithmetic (unit rides as a const string arg)
        "date_add", "date_sub", "datediff",
        # bit
        "bitand", "bitor", "bitxor", "bitneg", "shiftleft", "shiftright",
    }
)

# host-only custom functions added at runtime by the extension registry
# (ref: pkg/extension custom functions); never device-compiled — the DAG
# splitter pins expressions containing them to the root side
EXTENSION_OPS: set = set()


class Expr:
    """Base expression node. All nodes expose `.ft` and are hashable."""

    __slots__ = ()
    ft: FieldType

    def children(self) -> tuple["Expr", ...]:
        return ()

    def fingerprint(self) -> tuple:
        raise NotImplementedError


@dataclass(frozen=True)
class ColumnRef(Expr):
    """Reference to the i-th column of the child operator's output
    (ref: tipb.Expr ColumnRef with offset payload)."""

    index: int
    ft: FieldType

    def fingerprint(self) -> tuple:
        return ("col", self.index, self.ft.tp, int(self.ft.flag), self.ft.flen, self.ft.decimal)


@dataclass(frozen=True)
class Const(Expr):
    """A literal. The datum participates in the fingerprint so constant
    folding differences recompile (mirrors plan-cache parameterization —
    heavy reuse should parameterize instead; see exec/builder.py)."""

    datum: Datum
    ft: FieldType

    def fingerprint(self) -> tuple:
        v = self.datum.val
        key = str(v) if not isinstance(v, (int, float, str, bytes, type(None))) else v
        return ("const", self.datum.kind, key, self.ft.tp, self.ft.decimal)


@dataclass(frozen=True)
class ScalarFunc(Expr):
    op: str
    args: tuple
    ft: FieldType

    def __post_init__(self):
        if self.op not in SCALAR_OPS and self.op not in EXTENSION_OPS:
            raise ValueError(f"unknown scalar op {self.op!r}")

    def children(self) -> tuple[Expr, ...]:
        return self.args

    def fingerprint(self) -> tuple:
        return ("fn", self.op, self.ft.tp, int(self.ft.flag), self.ft.decimal) + tuple(
            a.fingerprint() for a in self.args
        )


# ---- convenience constructors ---------------------------------------------

def col(index: int, ft: FieldType) -> ColumnRef:
    return ColumnRef(index, ft)


def const(d: Datum, ft: FieldType) -> Const:
    return Const(d, ft)


def lit(v, ft: FieldType) -> Const:
    """Build a Const from a python value using the target FieldType."""
    from ..types import DatumKind, MyDecimal, MyTime

    if v is None:
        return Const(Datum.NULL, ft)
    if ft.is_decimal():
        return Const(Datum.dec(MyDecimal(v, max(ft.decimal, 0))), ft)
    if ft.is_float():
        return Const(Datum.f64(float(v)), ft)
    if ft.is_string():
        # keep str subclasses intact (plan-cache slot tags, plancache.SlotStr)
        return Const(Datum.string(v if isinstance(v, str) else str(v)), ft)
    if ft.is_time():
        t = v if isinstance(v, MyTime) else MyTime.parse(str(v), max(ft.decimal, 0))
        return Const(Datum.time(t), ft)
    if ft.is_unsigned():
        return Const(Datum.u64(int(v)), ft)
    return Const(Datum.i64(int(v)), ft)


def func(op: str, ft: FieldType, *args: Expr) -> ScalarFunc:
    return ScalarFunc(op, tuple(args), ft)
