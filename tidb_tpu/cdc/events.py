"""Changefeed event shapes (ref: TiCDC's model.RowChangedEvent — the
mounted, typed form of one row's change — and model.ResolvedTs).

A raw change enters the subsystem as a (key, value|None, commit_ts)
triple riding a replication proposal; the mounter decodes it back into a
`RowEvent` with the table's typed column values. Resolved timestamps are
not events in the sorter — they are the frontier the sink's `flush`
receives once every row at or below it has been emitted."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class RowEvent:
    """One row's change, decoded (ref: model.RowChangedEvent). `columns`
    is ((name, Datum), ...) in table column order — empty for deletes
    (the reference also omits new-values on delete; the old value is the
    downstream's to look up if it cares)."""

    table: str
    table_id: int
    handle: int
    op: str  # "put" | "delete"
    commit_ts: int
    columns: tuple = field(default=())

    def to_json(self) -> dict:
        """JSON-lines shape for the file sink (ref: TiCDC's canal-json /
        simple protocol: type + commit ts + column map)."""
        return {
            "type": "row",
            "table": self.table,
            "handle": self.handle,
            "op": self.op,
            "commit_ts": self.commit_ts,
            "columns": {
                name: (None if d.is_null() else d.val) for name, d in self.columns
            },
        }
