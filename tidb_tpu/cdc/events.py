"""Changefeed event shapes (ref: TiCDC's model.RowChangedEvent — the
mounted, typed form of one row's change — and model.ResolvedTs).

A raw change enters the subsystem as a (key, value|None, commit_ts)
triple riding a replication proposal; the mounter decodes it back into a
`RowEvent` with the table's typed column values. Resolved timestamps are
not events in the sorter — they are the frontier the sink's `flush`
receives once every row at or below it has been emitted."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class RowEvent:
    """One row's change, decoded (ref: model.RowChangedEvent). `columns`
    is ((name, Datum), ...) in table column order — empty for deletes
    (the reference also omits new-values on delete; the old value is the
    downstream's to look up if it cares)."""

    table: str
    table_id: int
    handle: int
    op: str  # "put" | "delete"
    commit_ts: int
    columns: tuple = field(default=())
    col_ids: tuple = field(default=())  # column ids aligned with `columns`
    # — the shape the mounter's schema tracker decoded against, so sinks
    # that hold their OWN schema snapshot (the columnar replica) can remap
    # by id instead of trusting the live catalog's column order

    def to_json(self) -> dict:
        """JSON-lines shape for the file sink (ref: TiCDC's canal-json /
        simple protocol: type + commit ts + column map)."""
        return {
            "type": "row",
            "table": self.table,
            "handle": self.handle,
            "op": self.op,
            "commit_ts": self.commit_ts,
            "columns": {
                name: (None if d.is_null() else d.val) for name, d in self.columns
            },
        }


@dataclass(frozen=True)
class SchemaEvent:
    """A schema change replicated THROUGH the feed as an ordered event
    (ref: TiCDC's DDLEvent riding the same sorted stream as row changes;
    ISSUE 20). `payload` is the full post-change column snapshot
    (cdc/schema.py's wire dict) — enough for a downstream to rebuild the
    table shape without consulting the source catalog. Rows before this
    event's commit_ts mounted against the PREVIOUS snapshot; rows after
    it mount against this one."""

    table: str
    table_id: int
    commit_ts: int
    schema_version: int
    op: str  # "add column" | "drop column" | ... (the DDL job type)
    query: str
    payload: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "type": "schema",
            "table": self.table,
            "table_id": self.table_id,
            "commit_ts": self.commit_ts,
            "schema_version": self.schema_version,
            "op": self.op,
            "query": self.query,
            "payload": self.payload,
        }


@dataclass(frozen=True)
class RawKVEvent:
    """One raw (undecoded) KV change for the log-backup feed (ref: BR's
    log backup streaming raw KV write batches, br/pkg/stream): PITR
    replay re-ingests these bytes at the source commit ts, so index
    entries and row bytes survive byte-exactly — no mount/re-encode
    round trip to drift through."""

    key: bytes
    value: bytes | None
    commit_ts: int

    def to_json(self) -> dict:
        return {
            "type": "kv",
            "k": self.key.hex(),
            "v": None if self.value is None else self.value.decode("latin1"),
            "commit_ts": self.commit_ts,
        }
