"""Change data capture (ISSUE 10): the TiCDC-analog changefeed
subsystem — puller over the replication log, commit-ts sorter,
resolved-ts frontier, rowcodec mounter, pluggable sinks."""

from .events import RowEvent
from .hub import Changefeed, ChangefeedError, ChangefeedHub, WriteGuard
from .mounter import Mounter, SchemaDriftError
from .sink import FileSink, MemorySink, SessionReplaySink, Sink, SinkError, open_sink

__all__ = [
    "RowEvent", "Changefeed", "ChangefeedError", "ChangefeedHub", "WriteGuard",
    "Mounter", "SchemaDriftError", "FileSink", "MemorySink",
    "SessionReplaySink", "Sink",
    "SinkError", "open_sink",
]
