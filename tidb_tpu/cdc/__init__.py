"""Change data capture (ISSUE 10): the TiCDC-analog changefeed
subsystem — puller over the replication log, commit-ts sorter,
resolved-ts frontier, rowcodec mounter, pluggable sinks. ISSUE 20 adds
schema-change entries riding the same log (DDL through the feed), raw
feeds for log backup, and atomic file-sink segments."""

from .events import RawKVEvent, RowEvent, SchemaEvent
from .hub import Changefeed, ChangefeedError, ChangefeedHub, WriteGuard
from .mounter import Mounter, SchemaDriftError
from .schema import SchemaJournal
from .sink import (
    FileSink, MemorySink, SegmentWriter, SessionReplaySink, Sink, SinkError,
    open_sink,
)

__all__ = [
    "RowEvent", "SchemaEvent", "RawKVEvent", "Changefeed", "ChangefeedError",
    "ChangefeedHub", "WriteGuard", "Mounter", "SchemaDriftError",
    "SchemaJournal", "FileSink", "MemorySink", "SegmentWriter",
    "SessionReplaySink", "Sink", "SinkError", "open_sink",
]
