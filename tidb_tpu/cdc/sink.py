"""Changefeed sinks (ref: TiCDC's cdc/sink — MQ/blackhole/MySQL sinks
behind one interface). Three concrete sinks:

  MemorySink         buffered events + resolved marks (tests, SHOW-style
                     introspection; the blackhole sink with a memory)
  FileSink           JSON-lines segments under a directory, one
                     subdirectory per changefeed (the storage sink
                     analog; each flush writes ONE atomic segment ending
                     in a resolved mark, so a consumer can cut complete
                     prefixes and a crash can never leave a torn tail)
  SessionReplaySink  applies the stream into a SECOND cluster through
                     its store write path (the MySQL-sink analog; the
                     mirror-equality oracle rides this one); schema
                     events apply the replicated DDL to the mirror
                     catalog (ISSUE 20)

The contract every sink honors: `write(events)` receives rows in
(commit_ts, key) order, all at or below the NEXT `flush(resolved_ts)` —
a flushed resolved ts promises the downstream holds a transactionally
complete prefix of the source."""

from __future__ import annotations

import json
import os
import threading


class SinkError(RuntimeError):
    """A sink rejected the stream (unknown downstream table, closed
    file): the changefeed parks in the `error` state with this message."""


def open_sink(uri: str, name: str):
    """Sink from a sink-uri (ref: TiCDC's --sink-uri schemes). Supported:
    `memory://` and `file://<dir>` (empty dir -> ./cdc-output). The
    session-replay sink needs a live target cluster and is registered via
    the hub API, not a URI."""
    scheme, _, rest = uri.partition("://")
    scheme = scheme.lower()
    if scheme == "memory":
        return MemorySink()
    if scheme == "file":
        return FileSink(rest or "cdc-output", name)
    raise SinkError(
        f"unsupported sink uri {uri!r} (memory:// | file://<dir>; "
        f"session-replay sinks attach via the changefeed API)")


class Sink:
    def write(self, events: list) -> None:
        raise NotImplementedError

    def flush(self, resolved_ts: int) -> None:
        """All events at or below `resolved_ts` are written: make them
        durable/visible downstream."""

    def close(self) -> None:
        pass

    def describe(self) -> str:
        return type(self).__name__


class MemorySink(Sink):
    def __init__(self):
        self._mu = threading.Lock()
        self.events: list = []  # guarded_by: _mu
        self.resolved: list = []  # flush watermarks, in order; guarded_by: _mu

    def write(self, events: list) -> None:
        with self._mu:
            self.events.extend(events)

    def flush(self, resolved_ts: int) -> None:
        with self._mu:
            self.resolved.append(resolved_ts)

    def rows(self) -> list:
        with self._mu:
            return list(self.events)

    def resolved_view(self) -> list:
        with self._mu:
            return list(self.resolved)

    def describe(self) -> str:
        return "memory://"


class SegmentWriter:
    """Atomic JSONL segment writer (ISSUE 20; ref: br/pkg/storage's
    write-then-rename local backend). Each segment is written whole to a
    `.tmp` sibling, fsync'd, then renamed into place — a segment is
    either fully present or absent, never a torn tail. Consumers read
    `seg-*.jsonl` in name order and ignore `*.tmp` leftovers."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._mu = threading.Lock()
        # resume past segments already durable (a re-attached sink must
        # never overwrite a committed segment); guarded_by: _mu
        self._next = 1 + max(
            (int(f[4:10]) for f in os.listdir(directory)
             if f.startswith("seg-") and f.endswith(".jsonl")), default=-1)

    def write_segment(self, lines: list) -> str:
        """One atomic segment of complete JSON lines; returns the file
        name. The tmp file is removed on failure so a crashed flush
        leaves nothing a consumer could mistake for data."""
        from ..util import failpoint

        with self._mu:
            fname = f"seg-{self._next:06d}.jsonl"
            tmp = os.path.join(self.directory, fname + ".tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                f.write("".join(line + "\n" for line in lines))
                f.flush()
                os.fsync(f.fileno())
            if failpoint.eval("cdc/segment-crash"):
                # the kill-mid-flush drill: the process "dies" with the
                # tmp written but never renamed in — the leftover MUST be
                # invisible to consumers (the torn-tail crash this
                # writer exists to fix), so it deliberately stays behind
                raise SinkError(
                    "cdc/segment-crash: killed between write and rename")
            try:
                os.replace(tmp, os.path.join(self.directory, fname))
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            self._next += 1
            return fname

    def segments(self) -> list:
        """Durable segment file names, in write order."""
        return sorted(f for f in os.listdir(self.directory)
                      if f.startswith("seg-") and f.endswith(".jsonl"))

    def read_records(self) -> list:
        """Every record of every durable segment, in order — the
        consumer's view (tmp leftovers and torn tails cannot appear:
        only renamed-in segments are read)."""
        out = []
        for fname in self.segments():
            with open(os.path.join(self.directory, fname), encoding="utf-8") as f:
                out.extend(json.loads(line) for line in f if line.strip())
        return out


class FileSink(Sink):
    """JSON-lines segments: `write` buffers the batch, `flush` commits
    it as ONE atomic segment (SegmentWriter: write-temp + fsync +
    rename) ending in a `{"type":"resolved","ts":N}` mark — any prefix
    of segments is a consistent cut, and a kill mid-flush leaves only
    whole segments behind (the torn-tail crash bug this replaced: a
    partial JSON line in an append-mode file poisoned every later read).
    A failed flush drops the buffer — the feed re-queues the batch below
    its held checkpoint and redelivers it to a fresh flush, so exactly
    one durable copy ever lands."""

    def __init__(self, directory: str, name: str):
        self.directory = os.path.join(directory, name)
        self.writer = SegmentWriter(self.directory)
        self._mu = threading.Lock()
        self._buf: list = []  # pending event lines; guarded_by: _mu

    def write(self, events: list) -> None:
        with self._mu:
            self._buf.extend(json.dumps(ev.to_json(), default=str) for ev in events)

    def flush(self, resolved_ts: int) -> None:
        with self._mu:
            lines, self._buf = self._buf, []
            if not lines:
                return  # quiet window: no empty segment spam per tick
            lines.append(json.dumps({"type": "resolved", "ts": resolved_ts}))
            self.writer.write_segment(lines)

    def read_records(self) -> list:
        return self.writer.read_records()

    def describe(self) -> str:
        return f"file://{self.directory}"


class SessionReplaySink(Sink):
    """Replays the stream into a second cluster through its store write
    path (rows only — the downstream's schema owns its indexes; create
    the mirror's tables without secondary indexes or rebuild them after).
    `flush` fast-forwards the mirror's TSO past the resolved frontier so
    a fresh mirror snapshot sees the complete replayed prefix.

    Delivery after a sink failure is AT-LEAST-ONCE from the last
    checkpoint (the reference's contract — TiCDC re-sends on recovery),
    so this sink is idempotent by (key, commit_ts): a version the mirror
    already holds at or past the event's ts is skipped, exactly like the
    MySQL sink's REPLACE-by-commit-ts semantics."""

    def __init__(self, session):
        self.session = session

    def _apply_schema(self, ev) -> None:
        """One replicated DDL onto the mirror catalog: rebuild the
        table's column list from the event payload (idempotent — a
        redelivered event at or below the mirror's version is a no-op).
        The mirror keeps consuming instead of parking (ISSUE 20)."""
        from ..sql.catalog import CatalogError, ColumnMeta
        from .schema import snapshot_from_payload

        catalog = self.session.catalog
        try:
            meta = catalog.table(ev.table)
        except CatalogError as exc:
            raise SinkError(f"replay: no downstream table for {ev.table!r}") from exc
        if meta.schema_version >= ev.schema_version:
            return  # redelivery / already applied
        snap = snapshot_from_payload(ev.payload)
        meta.columns = [
            ColumnMeta(c.name, c.col_id, c.ft, origin_default=c.origin_default)
            for c in snap.columns
        ]
        handle_col = ev.payload.get("handle_col")
        if handle_col:
            meta.handle_col = handle_col
        meta.next_col_id = max(meta.next_col_id,
                               ev.payload.get("next_col_id", 0),
                               max((c.col_id for c in snap.columns), default=0) + 1)
        meta.schema_version = ev.schema_version
        catalog.version += 1

    def write(self, events: list) -> None:
        from ..codec import tablecodec
        from ..sql.catalog import CatalogError
        from ..types import Datum
        from .events import SchemaEvent

        catalog = self.session.catalog
        store = self.session.store
        for ev in events:
            if isinstance(ev, SchemaEvent):
                self._apply_schema(ev)
                continue
            try:
                meta = catalog.table(ev.table)
            except CatalogError as exc:
                raise SinkError(f"replay: no downstream table for {ev.table!r}") from exc
            if ev.op == "delete":
                # the row's partition is value-dependent and deletes carry
                # no values: tombstone the handle in every physical id
                # (over-deleting is sound — absent keys tombstone to absent)
                for pid in meta.physical_ids():
                    key = tablecodec.encode_row_key(pid, ev.handle)
                    if store.kv.latest_ts(key) < ev.commit_ts:
                        store.delete_row(pid, ev.handle, ev.commit_ts)
                continue
            by_name = dict(ev.columns)
            datums = [by_name.get(c.name, Datum.NULL) for c in meta.columns]
            pid = meta.pid_for_row(datums)
            key = tablecodec.encode_row_key(pid, ev.handle)
            if store.kv.latest_ts(key) < ev.commit_ts:  # redelivery dedupe
                store.put_row(pid, ev.handle, meta.col_ids(), datums, ev.commit_ts)

    def flush(self, resolved_ts: int) -> None:
        self.session.store.advance_tso(resolved_ts)

    def describe(self) -> str:
        return "session-replay://"
