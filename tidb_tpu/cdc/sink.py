"""Changefeed sinks (ref: TiCDC's cdc/sink — MQ/blackhole/MySQL sinks
behind one interface). Three concrete sinks:

  MemorySink         buffered events + resolved marks (tests, SHOW-style
                     introspection; the blackhole sink with a memory)
  FileSink           JSON-lines under a directory, one file per
                     changefeed (the storage sink analog; resolved marks
                     interleave so a consumer can cut complete prefixes)
  SessionReplaySink  applies the stream into a SECOND cluster through
                     its store write path (the MySQL-sink analog; the
                     mirror-equality oracle rides this one)

The contract every sink honors: `write(events)` receives rows in
(commit_ts, key) order, all at or below the NEXT `flush(resolved_ts)` —
a flushed resolved ts promises the downstream holds a transactionally
complete prefix of the source."""

from __future__ import annotations

import json
import os
import threading


class SinkError(RuntimeError):
    """A sink rejected the stream (unknown downstream table, closed
    file): the changefeed parks in the `error` state with this message."""


def open_sink(uri: str, name: str):
    """Sink from a sink-uri (ref: TiCDC's --sink-uri schemes). Supported:
    `memory://` and `file://<dir>` (empty dir -> ./cdc-output). The
    session-replay sink needs a live target cluster and is registered via
    the hub API, not a URI."""
    scheme, _, rest = uri.partition("://")
    scheme = scheme.lower()
    if scheme == "memory":
        return MemorySink()
    if scheme == "file":
        return FileSink(rest or "cdc-output", name)
    raise SinkError(
        f"unsupported sink uri {uri!r} (memory:// | file://<dir>; "
        f"session-replay sinks attach via the changefeed API)")


class Sink:
    def write(self, events: list) -> None:
        raise NotImplementedError

    def flush(self, resolved_ts: int) -> None:
        """All events at or below `resolved_ts` are written: make them
        durable/visible downstream."""

    def close(self) -> None:
        pass

    def describe(self) -> str:
        return type(self).__name__


class MemorySink(Sink):
    def __init__(self):
        self._mu = threading.Lock()
        self.events: list = []  # guarded_by: _mu
        self.resolved: list = []  # flush watermarks, in order; guarded_by: _mu

    def write(self, events: list) -> None:
        with self._mu:
            self.events.extend(events)

    def flush(self, resolved_ts: int) -> None:
        with self._mu:
            self.resolved.append(resolved_ts)

    def rows(self) -> list:
        with self._mu:
            return list(self.events)

    def resolved_view(self) -> list:
        with self._mu:
            return list(self.resolved)

    def describe(self) -> str:
        return "memory://"


class FileSink(Sink):
    """JSON lines: one `{"type":"row",...}` per event, one
    `{"type":"resolved","ts":N}` per flush. Append-only — a restarted
    consumer replays from the last resolved mark it trusts."""

    def __init__(self, directory: str, name: str):
        self.path = os.path.join(directory, f"{name}.jsonl")
        os.makedirs(directory, exist_ok=True)
        self._mu = threading.Lock()
        self._f = open(self.path, "a", encoding="utf-8")  # guarded_by: _mu

    def write(self, events: list) -> None:
        with self._mu:
            for ev in events:
                self._f.write(json.dumps(ev.to_json(), default=str) + "\n")

    def flush(self, resolved_ts: int) -> None:
        with self._mu:
            self._f.write(json.dumps({"type": "resolved", "ts": resolved_ts}) + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._mu:
            self._f.close()

    def describe(self) -> str:
        return f"file://{self.path}"


class SessionReplaySink(Sink):
    """Replays the stream into a second cluster through its store write
    path (rows only — the downstream's schema owns its indexes; create
    the mirror's tables without secondary indexes or rebuild them after).
    `flush` fast-forwards the mirror's TSO past the resolved frontier so
    a fresh mirror snapshot sees the complete replayed prefix.

    Delivery after a sink failure is AT-LEAST-ONCE from the last
    checkpoint (the reference's contract — TiCDC re-sends on recovery),
    so this sink is idempotent by (key, commit_ts): a version the mirror
    already holds at or past the event's ts is skipped, exactly like the
    MySQL sink's REPLACE-by-commit-ts semantics."""

    def __init__(self, session):
        self.session = session

    def write(self, events: list) -> None:
        from ..codec import tablecodec
        from ..sql.catalog import CatalogError
        from ..types import Datum

        catalog = self.session.catalog
        store = self.session.store
        for ev in events:
            try:
                meta = catalog.table(ev.table)
            except CatalogError as exc:
                raise SinkError(f"replay: no downstream table for {ev.table!r}") from exc
            if ev.op == "delete":
                # the row's partition is value-dependent and deletes carry
                # no values: tombstone the handle in every physical id
                # (over-deleting is sound — absent keys tombstone to absent)
                for pid in meta.physical_ids():
                    key = tablecodec.encode_row_key(pid, ev.handle)
                    if store.kv.latest_ts(key) < ev.commit_ts:
                        store.delete_row(pid, ev.handle, ev.commit_ts)
                continue
            by_name = dict(ev.columns)
            datums = [by_name.get(c.name, Datum.NULL) for c in meta.columns]
            pid = meta.pid_for_row(datums)
            key = tablecodec.encode_row_key(pid, ev.handle)
            if store.kv.latest_ts(key) < ev.commit_ts:  # redelivery dedupe
                store.put_row(pid, ev.handle, meta.col_ids(), datums, ev.commit_ts)

    def flush(self, resolved_ts: int) -> None:
        self.session.store.advance_tso(resolved_ts)

    def describe(self) -> str:
        return "session-replay://"
