"""Changefeed hub: the TiCDC-analog CDC pipeline over the replication
log (ref: TiDB VLDB'20's log-based HTAP replication + TiCDC's
puller -> sorter -> mounter -> sink pipeline; DBLog-style incremental
scans interleaved with the live log).

One `ChangefeedHub` per TPUStore. Each `Changefeed` is the full
pipeline for one subscription:

  puller     `ReplicaManager.propose` hands every committed write batch
             (the raft-lite log entry) to `capture()`; a changefeed
             additionally owns INCREMENTAL SCANS (`MemKV.scan_versions`)
             that backfill (checkpoint, candidate] for ranges whose live
             subscription was lost — the initial scan at `start_ts` is
             just the whole keyspace being "lost" at birth, and the
             `cdc/puller-drop` failpoint re-creates the mid-stream form.
             Dedupe is by (key, commit_ts): a live capture and a
             recovery scan of the same write collapse to one event.
  sorter     the pending map drains in (commit_ts, key) order, only up
             to the resolved frontier — downstream never sees a commit
             before everything below it.
  frontier   min over subscribed regions' watermarks. Watermarks advance
             to a TSO candidate proven SAFE by a quiescent sample of the
             store's WriteGuard: every write path brackets
             [commit-ts draw .. capture delivery] in `writing()`, so a
             candidate drawn with no write in flight (and none completing
             between two samples) dominates every delivered and every
             future commit ts. `cdc/resolved-stuck` pins the advance.
  mounter    cdc/mounter.py decodes rows against the feed's TRACKED
             schema snapshots; schema-change entries in the log
             (cdc/schema.py, ISSUE 20) advance the tracker in commit-ts
             order and emit SchemaEvents downstream — a mid-feed ALTER
             replicates through the feed instead of parking it. A RAW
             feed (the BR log backup) skips mounting entirely and hands
             the sink undecoded RawKVEvents, index entries included.
  sink       cdc/sink.py; `cdc/sink-stall` skips a tick's emission
             (the frontier may advance internally, the emitted
             checkpoint — and the sink — stay put).

Schema entries are not in KV, so the incremental-scan recovery path
cannot backfill them: every tick additionally injects the store's
SchemaJournal window (checkpoint, candidate] into the sorter — the
(key, ts) dedupe absorbs the overlap with live captures.

The emitted checkpoint doubles as the feed's GC service safepoint
(ref: TiCDC's service GC safepoint in PD): the hub keeps a registered
snapshot at the checkpoint so MVCC GC can never collect a version the
feed still has to scan.

Lock order: hub._tick_mu -> feed._mu -> (metrics/kv leaf locks). The
capture path takes feed._mu with no other subsystem lock held
(`propose` notifies after releasing ReplicaManager._mu; commit's
on_apply runs outside the kv critical section). Cluster topology hooks
(`on_split`/`on_merge`) arrive under Cluster._mu, so feed.tick
snapshots the region list BEFORE taking feed._mu — never the reverse.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from ..store.region import KEY_MAX
from .events import RawKVEvent
from .mounter import Mounter, SchemaDriftError
from .schema import is_schema_key, schema_key_table_id
from .sink import Sink, SinkError, open_sink


class ChangefeedError(ValueError):
    """Lifecycle misuse (duplicate name, unknown feed, bad state) — the
    session boundary maps it onto a plain SQLError."""


class WriteGuard:
    """In-flight write tracker — the resolved-ts sampler's proof
    obligation. Writers bracket [commit-ts draw .. capture delivery] in
    `writing()`; `sample()` returns (inflight, completion seq). A TSO
    candidate drawn between two identical quiescent samples is a sound
    resolved-ts bound: no write was in flight across the draw, and any
    later write draws a larger commit ts from the monotone TSO."""

    def __init__(self):
        self._mu = threading.Lock()
        self._inflight = 0  # guarded_by: _mu
        self._seq = 0  # completed windows; guarded_by: _mu

    @contextmanager
    def writing(self):
        with self._mu:
            self._inflight += 1
        try:
            yield
        finally:
            with self._mu:
                self._inflight -= 1
                self._seq += 1

    def sample(self) -> tuple:
        with self._mu:
            return self._inflight, self._seq


class Changefeed:
    """One subscription's pipeline state. States: normal -> paused
    (PAUSE CHANGEFEED; capture stops, resume re-scans from the
    checkpoint) -> normal, or -> error (a sink/mount failure parks the
    feed with the message; RESUME retries), or removed (DROP)."""

    def __init__(self, hub, name: str, sink: Sink, catalog,
                 table_ids=None, start_ts: int = 0, raw: bool = False):
        self.hub = hub
        self.name = name
        self.sink = sink
        self.catalog = catalog
        self.mounter = Mounter(catalog)
        self.table_ids = frozenset(table_ids) if table_ids is not None else None
        # raw feeds (the BR log backup) skip the mounter: the sink gets
        # undecoded RawKVEvents (index entries included) so PITR replay
        # re-ingests the exact bytes at the source commit ts
        self.raw = raw
        # birth schema snapshot (ISSUE 12/20): every subscribed table's
        # row SHAPE is snapshotted NOW; a mid-feed ALTER advances it via
        # a replicated schema entry instead of parking the feed
        self.mounter.stamp_tables(self.table_ids)
        self.start_ts = start_ts
        self._mu = threading.Lock()
        self.state = "normal"  # guarded_by: _mu
        self.last_error = ""  # guarded_by: _mu
        self.checkpoint = start_ts  # emitted resolved frontier; guarded_by: _mu
        self._pending: dict = {}  # (key, commit_ts) -> value|None; guarded_by: _mu
        self._watermark: dict = {}  # region_id -> resolved watermark; guarded_by: _mu
        # key ranges whose live subscription lapsed (birth, puller-drop,
        # resume): recovered by incremental scan at the next tick
        self._lost: list = list(self._full_spans())  # guarded_by: _mu
        self.emitted = 0  # rows handed to the sink; guarded_by: _mu
        self.skipped = 0  # entries the mounter skipped; guarded_by: _mu

    def _full_spans(self) -> list:
        """The feed's whole subscription as key ranges: per-table
        prefixes for a filtered feed (a recovery scan must not
        materialize every OTHER table's versions under kv.lock just to
        discard them in Python), the whole keyspace otherwise."""
        from ..codec import tablecodec

        if self.table_ids is None:
            return [(b"", KEY_MAX)]
        return [(tablecodec.table_prefix(tid),
                 tablecodec.table_prefix(tid) + b"\xff")
                for tid in sorted(self.table_ids)]

    # ------------------------------------------------------------- puller
    def _wants(self, key: bytes) -> bool:
        """Table filter: record/index keys of subscribed tables, plus
        schema-change entries of subscribed tables (None = every table;
        the rest of the m-prefix meta keyspace never streams)."""
        from ..codec import tablecodec

        if is_schema_key(key):
            if self.table_ids is None:
                return True
            try:
                return schema_key_table_id(key) in self.table_ids
            except ValueError:
                return False
        if key[:1] != b"t" or len(key) < 9:
            return False
        if self.table_ids is None:
            return True
        try:
            return tablecodec.decode_key_table_id(key) in self.table_ids
        except Exception:  # noqa: BLE001 — malformed key: not table data
            return False

    def capture(self, region_id: int, ts: int, entries: list) -> None:
        """Live log entry from a replication proposal. `cdc/puller-drop`
        simulates a lost region subscription: the span is remembered and
        re-scanned from the checkpoint at the next tick, so nothing is
        lost — only late (exactly the reference's re-subscribe +
        incremental scan recovery)."""
        from ..util import failpoint, metrics

        kept = [(k, v) for k, v in entries if self._wants(k)]
        if not kept:
            return
        if failpoint.eval("cdc/puller-drop"):
            lo = min(k for k, _ in kept)
            hi = max(k for k, _ in kept) + b"\x00"
            with self._mu:
                if self.state == "normal":
                    self._lost.append((lo, hi))
            return
        fresh = 0
        with self._mu:
            if self.state != "normal":
                return  # paused/errored: resume recovers from checkpoint
            for k, v in kept:
                if (k, ts) not in self._pending:
                    self._pending[(k, ts)] = v
                    fresh += 1
        if fresh:
            metrics.CDC_EVENTS.inc(fresh)

    # --------------------------------------------- topology hand-offs
    # (called under Cluster._mu, exactly like flow/replica hooks: the
    # feed lock nests inside the cluster lock, never the reverse)
    def on_split(self, parent_id: int, child_id: int) -> None:
        with self._mu:
            self._watermark[child_id] = self._watermark.get(parent_id, self.checkpoint)

    def on_merge(self, left_id: int, right_id: int) -> None:
        with self._mu:
            right = self._watermark.pop(right_id, None)
            if right is not None:
                left = self._watermark.get(left_id, self.checkpoint)
                self._watermark[left_id] = min(left, right)

    # ----------------------------------------------------------- frontier
    def tick(self, store, region_ids: list, cand: int) -> int:
        """One pipeline turn under the hub's tick lock: recover lost
        spans, advance watermarks to `cand`, drain the sorter up to the
        frontier, mount and flush. Returns rows emitted."""
        from ..util import failpoint, metrics, tracing

        with self._mu:
            state = self.state
            checkpoint = self.checkpoint
        lag = max(store.kv.max_committed() - checkpoint, 0)
        metrics.CDC_RESOLVED_LAG.labels(self.name).set(lag)
        if state != "normal":
            return 0
        self._recover_lost(store, checkpoint, cand)
        self._inject_schema(store, checkpoint, cand)
        stuck = bool(failpoint.eval("cdc/resolved-stuck"))
        with self._mu:
            live = set(region_ids)
            for rid in region_ids:
                cur = self._watermark.get(rid, checkpoint)
                self._watermark[rid] = cur if stuck else max(cur, cand)
            for rid in [r for r in self._watermark if r not in live]:
                # a region that vanished between the topology snapshot and
                # now (merge) was folded by on_merge; anything left is a
                # stale entry that would pin the frontier forever
                del self._watermark[rid]
            frontier = min(self._watermark.values(), default=cand)
            frontier = max(frontier, checkpoint)
        if failpoint.eval("cdc/sink-stall"):
            return 0  # the sorter keeps the backlog; checkpoint holds
        with self._mu:
            batch = sorted(
                (ts, k, v) for (k, ts), v in self._pending.items() if ts <= frontier
            )
            for ts, k, _v in batch:
                del self._pending[(k, ts)]
        rows, skipped = [], 0
        try:
            for ts, k, v in batch:
                if self.raw:
                    # the log-backup feed: no mounting, exact bytes out
                    rows.append(RawKVEvent(k, v, ts))
                    continue
                if is_schema_key(k):
                    # a replicated DDL draining in commit-ts order:
                    # advance the tracked snapshot so later rows in THIS
                    # batch already decode against the new shape
                    ev = self.mounter.apply_schema(v, ts)
                    if ev is None:
                        skipped += 1  # stale/duplicate schema entry
                    else:
                        rows.append(ev)
                        metrics.CDC_SCHEMA_EVENTS.inc()
                    continue
                ev = self.mounter.mount(k, v, ts)
                if ev is None:
                    skipped += 1
                else:
                    rows.append(ev)
        except SchemaDriftError as exc:
            # the legacy park path (pre-ISSUE-20): the mounter now
            # resolves drift as a counted fallback and should never
            # raise, but a feed that still does parks safely with the
            # typed reason and re-queues the batch below the held
            # checkpoint — nothing is lost, sinks dedupe on redelivery
            with self._mu:
                self.state = "error"
                self.last_error = f"{type(exc).__name__}: {exc}"
                for ts, k, v in batch:
                    self._pending[(k, ts)] = v
            return 0
        t0 = time.monotonic()
        try:
            with tracing.span("cdc.flush", changefeed=self.name,
                              events=len(rows), resolved_ts=frontier):
                if rows:
                    self.sink.write(rows)
                self.sink.flush(frontier)
        except Exception as exc:  # noqa: BLE001 — a sink failure parks the
            # feed in `error` (ref: TiCDC changefeed error state); the
            # batch is NOT lost: it re-queues below the held checkpoint.
            # A partially-written batch therefore redelivers on RESUME —
            # AT-LEAST-ONCE across sink failures, the reference's
            # contract; sinks dedupe by (key, commit_ts)
            with self._mu:
                self.state = "error"
                self.last_error = f"{type(exc).__name__}: {exc}"
                for ts, k, v in batch:
                    self._pending[(k, ts)] = v
            return 0
        metrics.CDC_SINK_FLUSH.observe(time.monotonic() - t0)
        if rows:
            metrics.CDC_EVENTS_EMITTED.inc(len(rows))
        if skipped:
            metrics.CDC_EVENTS_SKIPPED.inc(skipped)
        self._advance_checkpoint(store, frontier, len(rows), skipped)
        return len(rows)

    def _recover_lost(self, store, checkpoint: int, cand: int) -> None:
        """Incremental scans for spans whose live subscription lapsed:
        every version in (checkpoint, cand] re-enters the sorter (dedupe
        by (key, commit_ts) absorbs the overlap with live captures)."""
        from ..util import metrics

        with self._mu:
            lost, self._lost = self._lost, []
        fresh = 0
        for lo, hi in lost:
            metrics.CDC_RECOVERY_SCANS.inc()
            versions = store.kv.scan_versions(lo, hi, checkpoint, cand)
            with self._mu:
                for k, ts, v in versions:
                    if self._wants(k) and (k, ts) not in self._pending:
                        self._pending[(k, ts)] = v
                        fresh += 1
        if fresh:
            metrics.CDC_EVENTS.inc(fresh)

    def _inject_schema(self, store, checkpoint: int, cand: int) -> None:
        """Schema entries in (checkpoint, cand] from the store journal:
        the live capture path delivers them too, but a feed whose
        subscription lapsed (pause, puller-drop, birth) cannot recover
        them by KV scan — the journal is the durable source. Dedupe by
        (key, commit_ts) absorbs the overlap."""
        journal = getattr(store, "schema_journal", None)
        if journal is None or not len(journal):
            return
        with self._mu:
            for k, ts, v in journal.entries_in(checkpoint, cand):
                if self._wants(k) and (k, ts) not in self._pending:
                    self._pending[(k, ts)] = v

    def _advance_checkpoint(self, store, frontier: int, emitted: int,
                            skipped: int) -> None:
        with self._mu:
            old = self.checkpoint
            self.checkpoint = max(self.checkpoint, frontier)
            new = self.checkpoint
            self.emitted += emitted
            self.skipped += skipped
            # the dedupe window below the checkpoint is closed: recovery
            # scans start above it, so those (key, ts) pairs cannot recur
            for key_ts in [kt for kt in self._pending if kt[1] <= new]:
                del self._pending[key_ts]
        if new != old:
            # slide the GC service safepoint (register-then-unregister:
            # the pin must never be absent in between)
            store.register_snapshot(new)
            store.unregister_snapshot(old)

    # ----------------------------------------------------------- lifecycle
    def pause(self) -> None:
        with self._mu:
            if self.state == "normal":
                self.state = "paused"

    def resume(self) -> None:
        """Back to normal with the whole keyspace marked lost: the next
        tick's incremental scan replays (checkpoint, now] — the pause
        window — before the frontier moves (ref: TiCDC resume doing an
        incremental catch-up from the checkpoint)."""
        drift_park = False
        with self._mu:
            if self.state in ("paused", "error"):
                drift_park = self.last_error.startswith("SchemaDriftError")
                self.state = "normal"
                self.last_error = ""
                self._lost.extend(self._full_spans())
        if drift_park:
            # RESUME doubles as the schema acknowledgment ONLY when the
            # park reason WAS the drift: the operator saw the typed
            # reason and accepted the new shape. A feed parked for an
            # unrelated reason (pause, a sink failure) keeps its birth
            # stamps — an ALTER that landed while it was parked must
            # still park it at the next mount, never mount the old-shape
            # backlog against the new catalog silently (review finding)
            self.mounter.restamp()

    def view(self, store) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "state": self.state,
                "sink": self.sink.describe(),
                "start_ts": self.start_ts,
                "checkpoint_ts": self.checkpoint,
                "resolved_lag": max(store.kv.max_committed() - self.checkpoint, 0),
                "pending": len(self._pending),
                "emitted": self.emitted,
                "skipped": self.skipped,
                "error": self.last_error,
                "tables": sorted(self.table_ids) if self.table_ids is not None else "all",
            }


class ChangefeedHub:
    """All changefeeds of one store + the shared WriteGuard. `tick()` is
    the `pd.cdc` phase's body and the sink flush loop's driver."""

    def __init__(self, store):
        self.store = store
        self.guard = WriteGuard()
        self._mu = threading.Lock()
        self._feeds: dict = {}  # name -> Changefeed; guarded_by: _mu
        # lock-free capture fast path: an immutable tuple swapped under
        # _mu, read GIL-atomically by every write's delivery
        self._capturing: tuple = ()
        self._tick_mu = threading.Lock()  # serializes whole ticks (sink
        # emission order is the resolved contract; concurrent ticks could
        # interleave two batches)
        store.cluster.cdc = self

    # ------------------------------------------------------------ capture
    def on_proposal(self, region_id: int, ts: int, entries: list) -> None:
        """Replication-log subscription: every committed write batch
        lands here (called by ReplicaManager.propose AFTER it releases
        its own lock)."""
        for feed in self._capturing:
            feed.capture(region_id, ts, entries)

    def on_split(self, parent_id: int, child_id: int) -> None:
        for feed in self._capturing:
            feed.on_split(parent_id, child_id)

    def on_merge(self, left_id: int, right_id: int) -> None:
        for feed in self._capturing:
            feed.on_merge(left_id, right_id)

    # ---------------------------------------------------------- lifecycle
    def create(self, name: str, sink, catalog, table_ids=None,
               start_ts: int = 0, raw: bool = False):
        """`sink` is a Sink instance or a sink-uri string. The new feed's
        first tick runs the initial incremental scan at `start_ts`.
        `raw=True` makes a log-backup-style feed that skips the mounter
        (the sink receives RawKVEvents, index entries included)."""
        opened_here = isinstance(sink, str)
        if opened_here:
            sink = open_sink(sink, name)
        feed = Changefeed(self, name, sink, catalog, table_ids, start_ts, raw=raw)
        # GC service safepoint at the checkpoint BEFORE the feed becomes
        # tickable (TiCDC's PD service safepoint): _advance_checkpoint's
        # register-new/unregister-old slide assumes the old pin exists —
        # registering after publication raced an in-flight tick and left
        # a refcounted pin behind forever (review finding)
        self.store.register_snapshot(feed.checkpoint)
        with self._mu:
            if name in self._feeds:
                self.store.unregister_snapshot(feed.checkpoint)
                if opened_here:  # a caller-owned sink stays the caller's
                    sink.close()
                raise ChangefeedError(f"changefeed {name!r} already exists")
            self._feeds[name] = feed
            self._capturing = tuple(self._feeds.values())
        return feed

    def get(self, name: str):
        with self._mu:
            feed = self._feeds.get(name)
        if feed is None:
            raise ChangefeedError(f"unknown changefeed {name!r}")
        return feed

    def pause(self, name: str) -> None:
        self.get(name).pause()

    def resume(self, name: str) -> None:
        self.get(name).resume()

    def drop(self, name: str) -> None:
        with self._mu:
            feed = self._feeds.pop(name, None)
            self._capturing = tuple(self._feeds.values())
        if feed is None:
            raise ChangefeedError(f"unknown changefeed {name!r}")
        # serialize against an in-flight tick (the PD timer thread):
        # its _advance_checkpoint slides the GC pin and its emission
        # writes the sink — both must finish (or see `removed` and never
        # start) before the pin is released and the sink closed, else
        # the pin double-releases at the old ts and re-registers at the
        # new one forever (review finding)
        with self._tick_mu:
            with feed._mu:
                checkpoint = feed.checkpoint
                feed.state = "removed"
            self.store.unregister_snapshot(checkpoint)
            feed.sink.close()
        from ..util import metrics

        # a dropped feed must not haunt dashboards with its last lag
        metrics.CDC_RESOLVED_LAG.labels(name).set(0)

    def feeds(self) -> list:
        with self._mu:
            return list(self._feeds.values())

    def views(self) -> list:
        return [f.view(self.store) for f in self.feeds()]

    # ----------------------------------------------------------- frontier
    def _safe_candidate(self) -> int | None:
        """A TSO candidate proven to dominate every delivered commit:
        sampled between two identical quiescent WriteGuard states.
        Bounded attempts, no sleep — a write-saturated interval simply
        keeps the previous frontier until the next tick."""
        for _attempt in range(8):
            inflight, seq = self.guard.sample()
            if inflight:
                continue
            cand = self.store.next_ts()
            inflight2, seq2 = self.guard.sample()
            if inflight2 == 0 and seq2 == seq:
                return cand
        return None

    def tick(self) -> int:
        """One frontier round for every feed (the `pd.cdc` phase body
        and the sink flush loop). Returns total rows emitted."""
        if not self.feeds():
            return 0
        with self._tick_mu:
            feeds = self.feeds()  # re-snapshot under the tick lock so a
            # feed dropped while we waited is never ticked post-close
            cand = self._safe_candidate()
            if cand is None:
                return 0
            # topology snapshot BEFORE any feed lock (Cluster._mu ->
            # feed._mu is the hook path's order; never invert it)
            region_ids = [r.region_id for r in self.store.cluster.regions()]
            return sum(f.tick(self.store, region_ids, cand) for f in feeds)
