"""Schema-change entries in the replication log (ISSUE 20: DDL
replication through the feed; ref: TiCDC's schema storage /
schemaStorage.HandleDDLJob keeping a multi-version schema snapshot so
rows mount against the version they were WRITTEN under, not the current
catalog).

A row-shape DDL (add/drop/modify/rename column) proposes a synthetic
log entry through `ReplicaManager.propose` exactly like a row write:
key = `m_schema_<table_id>_<version>` (the `m` meta keyspace — never a
real KV key), value = the JSON payload below, commit ts drawn from the
TSO inside the CDC WriteGuard so the resolved-ts frontier cannot pass
an undelivered schema change. The sorter orders it between the rows
committed before and after the ALTER, and the mounter's schema tracker
advances when the entry drains — a mid-feed ALTER is an ordered event,
not a park.

Schema entries are NOT in KV, so a feed whose live subscription lapsed
(pause, puller-drop, birth) cannot recover them with an incremental
`scan_versions` — that is what the store-level `SchemaJournal` is for:
every feed tick injects the journal's (checkpoint, candidate] window
into its sorter, and the (key, ts) dedupe absorbs the overlap with live
captures.

Payload wire shape (the log-backup segments persist it verbatim):

    {"table_id": N, "table": name, "schema_version": V,
     "op": job type, "query": DDL text, "handle_col": name|null,
     "next_col_id": N,
     "columns": [{"name", "col_id", "ft": {...}, "origin_default": {...}}]}
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass

SCHEMA_PREFIX = b"m_schema_"


def encode_schema_key(table_id: int, version: int) -> bytes:
    return SCHEMA_PREFIX + f"{table_id}_{version}".encode()


def is_schema_key(key: bytes) -> bool:
    return key.startswith(SCHEMA_PREFIX)


def schema_key_table_id(key: bytes) -> int:
    """Logical table id a schema entry belongs to — the feed's table
    filter routes on it. Raises ValueError on a malformed key (the
    caller treats that as not-wanted)."""
    rest = key[len(SCHEMA_PREFIX):]
    return int(rest.split(b"_", 1)[0])


@dataclass(frozen=True)
class ColumnSnap:
    """One column of a tracked schema snapshot — everything the mounter
    needs to decode row bytes written under this version."""

    name: str
    col_id: int
    ft: object  # FieldType
    origin_default: object  # Datum | None


@dataclass(frozen=True)
class SchemaSnapshot:
    """One table's row shape at one schema version (the mounter's
    per-feed tracked state; ref: TiCDC schema-tracker snapshot)."""

    version: int
    columns: tuple  # (ColumnSnap, ...)


def snapshot_from_meta(meta) -> SchemaSnapshot:
    return SchemaSnapshot(
        meta.schema_version,
        tuple(ColumnSnap(c.name, c.col_id, c.ft, c.origin_default)
              for c in meta.columns))


def schema_payload(meta, op: str, query: str) -> dict:
    """The wire dict for one schema-change entry (see module doc). Uses
    the BR field-type/datum codecs — the same round trip the full-backup
    manifest already proves."""
    from ..tools.br import _datum_to_dict, _ft_to_dict

    return {
        "table_id": meta.table_id,
        "table": meta.name,
        "schema_version": meta.schema_version,
        "op": op,
        "query": query,
        "handle_col": meta.handle_col,
        "next_col_id": meta.next_col_id,
        "columns": [
            {"name": c.name, "col_id": c.col_id, "ft": _ft_to_dict(c.ft),
             "origin_default": _datum_to_dict(c.origin_default)}
            for c in meta.columns
        ],
    }


def decode_payload(value: bytes) -> dict:
    return json.loads(value.decode())


def snapshot_from_payload(payload: dict) -> SchemaSnapshot:
    from ..tools.br import _datum_from_dict, _ft_from_dict

    return SchemaSnapshot(
        payload["schema_version"],
        tuple(ColumnSnap(c["name"], c["col_id"], _ft_from_dict(c["ft"]),
                         _datum_from_dict(c.get("origin_default")))
              for c in payload["columns"]))


class SchemaJournal:
    """Store-level ordered log of schema-change entries — the recovery
    source for schema events (they are not in KV, so incremental scans
    cannot backfill them; see module doc). Append-only, tiny (one entry
    per row-shape DDL), trimmed below the GC safepoint by the pd.pitr
    tick once no feed can still need the window."""

    def __init__(self):
        self._mu = threading.Lock()
        self._entries: list = []  # [(ts, table_id, key, value)] ascending ts; guarded_by: _mu

    def append(self, ts: int, table_id: int, key: bytes, value: bytes) -> None:
        with self._mu:
            self._entries.append((ts, table_id, key, value))

    def entries_in(self, lo: int, hi: int) -> list:
        """Entries with lo < ts <= hi as [(key, ts, value)] — the same
        triple shape `scan_versions` hands the recovery path."""
        with self._mu:
            return [(k, ts, v) for ts, _tid, k, v in self._entries
                    if lo < ts <= hi]

    def trim(self, below_ts: int) -> int:
        """Drop entries at or below `below_ts` (every feed's checkpoint
        passed them and no log backup can still replay them). Returns
        entries dropped."""
        with self._mu:
            n0 = len(self._entries)
            self._entries = [e for e in self._entries if e[0] > below_ts]
            return n0 - len(self._entries)

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)
