"""The mounter: raw KV change -> typed row event (ref: TiCDC's
cdc/entry/mounter.go — it decodes the raft-log value bytes back into
column datums against the schema snapshot the row was WRITTEN under).

Only RECORD keys mount (`t{tid}_r{handle}`): index entries are derived
data the downstream rebuilds itself, and non-table keyspaces (the
m-prefix schema metadata) are not row changes — both return None and the
caller counts them as skipped. Partitioned tables mount through the
partition's physical id back to the LOGICAL table meta, exactly like the
reference resolves PartitionDefinition.ID -> TableInfo.

Schema tracking (ISSUE 20): the mounter keeps a per-feed SNAPSHOT of
every subscribed table's column shape (`SchemaSnapshot`, not just a
version int). Rows decode against the TRACKED snapshot; a schema-change
entry draining through the sorter calls `apply_schema`, which advances
the snapshot and yields a `SchemaEvent` for the sink — so a mid-feed
ALTER replicates as an ordered event instead of parking the feed.
`SchemaDriftError` survives only as a counted legacy fallback: a row
whose bytes no longer decode against the tracked snapshot (a schema
move the journal never explained) re-decodes against the live catalog
and counts CDC_SCHEMA_DRIFT_LEGACY instead of wedging the pipeline."""

from __future__ import annotations

import threading

from ..codec import tablecodec
from ..codec.rowcodec import decode_row_to_datum_map, fill_origin_default
from .events import RowEvent, SchemaEvent
from .schema import SchemaSnapshot, decode_payload, snapshot_from_meta, snapshot_from_payload


class SchemaDriftError(RuntimeError):
    """A table's ROW-SHAPE schema moved under a live changefeed with no
    schema-change entry in the log to explain it (the pre-ISSUE-20 park
    signal, kept as a TYPED name for the counted legacy-fallback path:
    the mounter re-snapshots the live catalog and keeps mounting instead
    of parking, but the drift is still visible in metrics)."""

    def __init__(self, table: str, stamped: int, current: int):
        super().__init__(
            f"schema drift: table {table!r} changed mid-feed "
            f"(tracked version {stamped}, now {current}) — "
            f"re-decoded against the live catalog (counted legacy fallback)")
        self.table = table
        self.stamped = stamped
        self.current = current


class Mounter:
    """Decodes change values against per-table tracked schema snapshots.
    The pid->meta map rebuilds whenever the catalog version moves. Each
    table's snapshot seeds from the CURRENT catalog the first time the
    mounter sees it (or up front via `stamp_tables` — the feed's birth
    snapshot) and then advances ONLY through `apply_schema` — the
    replicated DDL stream, not the live catalog, drives the decode
    shape."""

    def __init__(self, catalog):
        self.catalog = catalog
        self._mu = threading.Lock()
        self._by_pid: dict = {}  # physical table id -> TableMeta; guarded_by: _mu
        self._cat_version = -1  # guarded_by: _mu
        self._tracked: dict = {}  # table_id -> SchemaSnapshot; guarded_by: _mu

    def _meta_for(self, pid: int):
        """-> (meta, tracked SchemaSnapshot) — (None, None) for an
        unknown pid. ONE critical section covers the map refresh, the
        lookup AND the first-sight snapshot (a second acquisition per
        event would double-lock the CDC hot mount loop; review
        finding)."""
        with self._mu:
            if self._cat_version != self.catalog.version:
                by_pid: dict = {}
                for name in self.catalog.tables():
                    try:
                        meta = self.catalog.table(name)
                    except Exception:  # noqa: BLE001 — a racing DROP TABLE
                        continue  # must not kill the mount loop
                    for p in meta.physical_ids():
                        by_pid[p] = meta
                self._by_pid = by_pid
                self._cat_version = self.catalog.version
            meta = self._by_pid.get(pid)
            if meta is None:
                return None, None
            snap = self._tracked.get(meta.table_id)
            if snap is None:
                snap = self._tracked[meta.table_id] = snapshot_from_meta(meta)
            return meta, snap

    def stamp_tables(self, table_ids=None) -> None:
        """Snapshot the CURRENT row shape of every (subscribed) table —
        the feed's birth schema snapshot. Tables first seen later
        snapshot lazily in mount()."""
        for name in self.catalog.tables():
            try:
                meta = self.catalog.table(name)
            except Exception:  # noqa: BLE001 — a racing DROP TABLE
                continue
            if table_ids is not None and meta.table_id not in table_ids and not any(
                    p in table_ids for p in meta.physical_ids()):
                continue
            with self._mu:
                self._tracked.setdefault(meta.table_id, snapshot_from_meta(meta))

    def restamp(self) -> None:
        """Drop every tracked snapshot: the next mount re-snapshots at
        the then-current catalog shape (RESUME's legacy escape hatch for
        feeds whose schema stream lapsed entirely)."""
        with self._mu:
            self._tracked.clear()

    def apply_schema(self, value: bytes, commit_ts: int) -> SchemaEvent | None:
        """One schema-change entry draining through the sorter: advance
        the tracked snapshot and return the SchemaEvent for the sink.
        Returns None (the caller counts a skip) when the entry is STALE —
        at or below the tracked version, e.g. a journal re-injection
        after the feed's birth snapshot already included the change, or
        a (key, ts) redelivery."""
        try:
            payload = decode_payload(value)
        except (ValueError, KeyError):
            return None  # malformed entry: skip, never wedge the feed
        tid = payload["table_id"]
        snap = snapshot_from_payload(payload)
        with self._mu:
            cur = self._tracked.get(tid)
            if cur is not None and snap.version <= cur.version:
                return None
            self._tracked[tid] = snap
        # the event wears the table's CURRENT name (RENAME TABLE mutates
        # meta in place and downstream lookups follow the live name)
        name = payload["table"]
        meta = self._by_pid.get(tid)  # vet: ignore[lock-discipline] — GIL-atomic probe
        if meta is not None:
            name = meta.name
        return SchemaEvent(name, tid, commit_ts, snap.version,
                           payload.get("op", "alter"),
                           payload.get("query", ""), payload)

    def _decode(self, meta, snap: SchemaSnapshot, value: bytes):
        fts_by_id = {c.col_id: c.ft for c in snap.columns}
        dmap = decode_row_to_datum_map(value, fts_by_id)
        return tuple(
            (c.name, fill_origin_default(value, c.col_id, c.origin_default, dmap[c.col_id]))
            for c in snap.columns
        )

    def mount(self, key: bytes, value: bytes | None, commit_ts: int) -> RowEvent | None:
        """One raw change -> RowEvent, or None when the key is not a row
        of a known table (index entry, meta keyspace, dropped table).
        Decodes against the TRACKED snapshot; on failure, falls back to
        the live catalog shape as a counted SchemaDriftError legacy
        fallback (never a park)."""
        try:
            pid, handle = tablecodec.decode_row_key(key)
        except ValueError:
            return None  # index/meta key: derived data, the caller skips
        meta, snap = self._meta_for(pid)
        if meta is None:
            return None
        if value is None:
            return RowEvent(meta.name, meta.table_id, handle, "delete", commit_ts)
        try:
            cols = self._decode(meta, snap, value)
        except Exception:  # noqa: BLE001 — bytes the tracked snapshot
            # cannot explain: a schema move the log never carried (the
            # pre-ISSUE-20 drift park). Fall back to the live catalog
            # shape, count it, and re-track so the next rows decode on
            # the first try.
            from ..util import metrics

            live = snapshot_from_meta(meta)
            try:
                cols = self._decode(meta, live, value)
            except Exception:  # noqa: BLE001 — undecodable either way:
                return None  # skip, never wedge the feed
            metrics.CDC_SCHEMA_DRIFT_LEGACY.inc()
            with self._mu:
                self._tracked[meta.table_id] = live
            snap = live
        return RowEvent(meta.name, meta.table_id, handle, "put", commit_ts, cols,
                        tuple(c.col_id for c in snap.columns))
