"""The mounter: raw KV change -> typed row event (ref: TiCDC's
cdc/entry/mounter.go — it decodes the raft-log value bytes back into
column datums against the current schema snapshot).

Only RECORD keys mount (`t{tid}_r{handle}`): index entries are derived
data the downstream rebuilds itself, and non-table keyspaces (the
m-prefix schema metadata) are not row changes — both return None and the
caller counts them as skipped. Partitioned tables mount through the
partition's physical id back to the LOGICAL table meta, exactly like the
reference resolves PartitionDefinition.ID -> TableInfo."""

from __future__ import annotations

import threading

from ..codec import tablecodec
from ..codec.rowcodec import decode_row_to_datum_map, fill_origin_default
from .events import RowEvent


class SchemaDriftError(RuntimeError):
    """A table's ROW-SHAPE schema version moved under a live changefeed
    (ISSUE 12 satellite; ref: TiCDC's schema-tracker keeping a snapshot
    per schema version — without one, a mid-feed ALTER would silently
    mount old row bytes against the NEW catalog and corrupt the mirror).
    The feed parks in `error` with this as the typed reason; RESUME
    re-stamps to the current schema (the operator's acknowledgment)."""

    def __init__(self, table: str, stamped: int, current: int):
        super().__init__(
            f"schema drift: table {table!r} changed mid-feed "
            f"(stamped version {stamped}, now {current}) — "
            f"RESUME the changefeed to accept the new schema")
        self.table = table
        self.stamped = stamped
        self.current = current


class Mounter:
    """Decodes change values against a catalog snapshot. The pid->meta
    map rebuilds whenever the catalog version moves. Each table's
    ROW-SHAPE version (`TableMeta.schema_version`) is STAMPED the first
    time the mounter sees it (or up front via `stamp_tables`); a row
    arriving after the version moved raises SchemaDriftError instead of
    silently mounting against the new catalog — the feed's park signal."""

    def __init__(self, catalog):
        self.catalog = catalog
        self._mu = threading.Lock()
        self._by_pid: dict = {}  # physical table id -> TableMeta; guarded_by: _mu
        self._cat_version = -1  # guarded_by: _mu
        self._stamps: dict = {}  # table_id -> schema_version at first sight; guarded_by: _mu

    def _meta_for(self, pid: int):
        """-> (meta, stamped schema version) — (None, 0) for an unknown
        pid. ONE critical section covers the map refresh, the lookup AND
        the first-sight stamp (a second acquisition per event would
        double-lock the CDC hot mount loop; review finding)."""
        with self._mu:
            if self._cat_version != self.catalog.version:
                by_pid: dict = {}
                for name in self.catalog.tables():
                    try:
                        meta = self.catalog.table(name)
                    except Exception:  # noqa: BLE001 — a racing DROP TABLE
                        continue  # must not kill the mount loop
                    for p in meta.physical_ids():
                        by_pid[p] = meta
                self._by_pid = by_pid
                self._cat_version = self.catalog.version
            meta = self._by_pid.get(pid)
            if meta is None:
                return None, 0
            return meta, self._stamps.setdefault(meta.table_id, meta.schema_version)

    def stamp_tables(self, table_ids=None) -> None:
        """Record the CURRENT row-shape version of every (subscribed)
        table — the feed's birth schema snapshot. Tables first seen later
        stamp lazily in mount()."""
        for name in self.catalog.tables():
            try:
                meta = self.catalog.table(name)
            except Exception:  # noqa: BLE001 — a racing DROP TABLE
                continue
            if table_ids is not None and meta.table_id not in table_ids and not any(
                    p in table_ids for p in meta.physical_ids()):
                continue
            with self._mu:
                self._stamps.setdefault(meta.table_id, meta.schema_version)

    def restamp(self) -> None:
        """Drop every stamp (RESUME's schema acknowledgment): the next
        mount re-stamps at the then-current version and the feed carries
        on against the NEW catalog."""
        with self._mu:
            self._stamps.clear()

    def mount(self, key: bytes, value: bytes | None, commit_ts: int) -> RowEvent | None:
        """One raw change -> RowEvent, or None when the key is not a row
        of a known table (index entry, meta keyspace, dropped table).
        Raises SchemaDriftError when the row's table changed shape since
        the feed stamped it — the caller parks the feed, never mounts."""
        try:
            pid, handle = tablecodec.decode_row_key(key)
        except ValueError:
            return None  # index/meta key: derived data, the caller skips
        meta, stamped = self._meta_for(pid)
        if meta is None:
            return None
        if meta.schema_version != stamped:
            raise SchemaDriftError(meta.name, stamped, meta.schema_version)
        if value is None:
            return RowEvent(meta.name, meta.table_id, handle, "delete", commit_ts)
        fts_by_id = {c.col_id: c.ft for c in meta.columns}
        try:
            dmap = decode_row_to_datum_map(value, fts_by_id)
            cols = tuple(
                (c.name, fill_origin_default(value, c.col_id, c.origin_default, dmap[c.col_id]))
                for c in meta.columns
            )
        except Exception:  # noqa: BLE001 — an undecodable value (schema
            return None  # drifted under the row) skips, never wedges the feed
        return RowEvent(meta.name, meta.table_id, handle, "put", commit_ts, cols)
