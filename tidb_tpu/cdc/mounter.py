"""The mounter: raw KV change -> typed row event (ref: TiCDC's
cdc/entry/mounter.go — it decodes the raft-log value bytes back into
column datums against the current schema snapshot).

Only RECORD keys mount (`t{tid}_r{handle}`): index entries are derived
data the downstream rebuilds itself, and non-table keyspaces (the
m-prefix schema metadata) are not row changes — both return None and the
caller counts them as skipped. Partitioned tables mount through the
partition's physical id back to the LOGICAL table meta, exactly like the
reference resolves PartitionDefinition.ID -> TableInfo."""

from __future__ import annotations

import threading

from ..codec import tablecodec
from ..codec.rowcodec import decode_row_to_datum_map, fill_origin_default
from .events import RowEvent


class Mounter:
    """Decodes change values against a catalog snapshot. The pid->meta
    map rebuilds whenever the catalog version moves (DDL between events:
    rows mount against the CURRENT schema, the reference's behavior for
    a changefeed without a schema-tracker snapshot)."""

    def __init__(self, catalog):
        self.catalog = catalog
        self._mu = threading.Lock()
        self._by_pid: dict = {}  # physical table id -> TableMeta; guarded_by: _mu
        self._cat_version = -1  # guarded_by: _mu

    def _meta_for(self, pid: int):
        with self._mu:
            if self._cat_version != self.catalog.version:
                by_pid: dict = {}
                for name in self.catalog.tables():
                    try:
                        meta = self.catalog.table(name)
                    except Exception:  # noqa: BLE001 — a racing DROP TABLE
                        continue  # must not kill the mount loop
                    for p in meta.physical_ids():
                        by_pid[p] = meta
                self._by_pid = by_pid
                self._cat_version = self.catalog.version
            return self._by_pid.get(pid)

    def mount(self, key: bytes, value: bytes | None, commit_ts: int) -> RowEvent | None:
        """One raw change -> RowEvent, or None when the key is not a row
        of a known table (index entry, meta keyspace, dropped table)."""
        try:
            pid, handle = tablecodec.decode_row_key(key)
        except ValueError:
            return None  # index/meta key: derived data, the caller skips
        meta = self._meta_for(pid)
        if meta is None:
            return None
        if value is None:
            return RowEvent(meta.name, meta.table_id, handle, "delete", commit_ts)
        fts_by_id = {c.col_id: c.ft for c in meta.columns}
        try:
            dmap = decode_row_to_datum_map(value, fts_by_id)
            cols = tuple(
                (c.name, fill_origin_default(value, c.col_id, c.origin_default, dmap[c.col_id]))
                for c in meta.columns
            )
        except Exception:  # noqa: BLE001 — an undecodable value (schema
            return None  # drifted under the row) skips, never wedges the feed
        return RowEvent(meta.name, meta.table_id, handle, "put", commit_ts, cols)
