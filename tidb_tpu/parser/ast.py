"""AST node definitions (ref: pkg/parser/ast — expressions.go, dml.go,
ddl.go, misc.go). Plain dataclasses; the planner walks these."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# ---------------------------------------------------------------- expressions


class ExprNode:
    __slots__ = ()


@dataclass
class Literal(ExprNode):
    """NULL / int / float-as-Decimal / string literal (ref: ast ValueExpr).

    `pos` is the source byte offset of the masked lexer token this
    literal came from (-1: synthesized, not a maskable token; -2: an
    uncacheable multi-token/transformed shape) — the plan cache's literal
    SLOT ordinal derives from it (sql/plancache.py), matching the
    token-order normalization the statement digest uses. Excluded from
    ast_digest (Literal nodes mask whole)."""

    value: object  # None | int | Decimal-string tuple | str | bytes
    kind: str  # "null" | "int" | "float" | "decimal" | "str" | "hex" | "bool"
    pos: int = -1


@dataclass
class ParamMarker(ExprNode):
    index: int
    pos: int = -1  # source byte offset of the '?' token (plan-cache slot)


@dataclass
class ColumnName(ExprNode):
    name: str
    table: str = ""
    db: str = ""

    def __str__(self):
        parts = [p for p in (self.db, self.table, self.name) if p]
        return ".".join(parts)


@dataclass
class Star(ExprNode):
    table: str = ""  # t.* when set
    db: str = ""  # db.t.* when set


@dataclass
class BinaryOp(ExprNode):
    op: str  # normalized lowercase: plus/minus/mul/div/intdiv/mod/eq/ne/lt/le/gt/ge/nulleq/and/or/xor/bitand/bitor/bitxor/shiftleft/shiftright
    left: ExprNode
    right: ExprNode


@dataclass
class UnaryOp(ExprNode):
    op: str  # not / unaryminus / bitneg
    operand: ExprNode


@dataclass
class FuncCall(ExprNode):
    name: str  # lowercase
    args: list = field(default_factory=list)


@dataclass
class AggFunc(ExprNode):
    name: str  # count/sum/avg/min/max/group_concat/bit_and/bit_or/bit_xor/stddev/var_pop...
    args: list = field(default_factory=list)
    distinct: bool = False
    order_by: list = field(default_factory=list)  # GROUP_CONCAT(... ORDER BY ...)
    separator: Optional[str] = None  # GROUP_CONCAT(... SEPARATOR s)


@dataclass
class WindowFunc(ExprNode):
    """fn(args) OVER (PARTITION BY ... ORDER BY ...) (ref: ast.WindowFuncExpr).

    has_frame marks an explicit non-default ROWS/RANGE clause — the planner
    rejects those at lowering (default frames only on device)."""

    name: str
    args: list  # [ExprNode]
    partition_by: list = field(default_factory=list)  # [ExprNode]
    order_by: list = field(default_factory=list)  # [ByItem]
    has_frame: bool = False


@dataclass
class IsNull(ExprNode):
    expr: ExprNode
    negated: bool = False


@dataclass
class IsTruth(ExprNode):
    expr: ExprNode
    truth: bool  # IS TRUE / IS FALSE
    negated: bool = False


@dataclass
class Between(ExprNode):
    expr: ExprNode
    low: ExprNode
    high: ExprNode
    negated: bool = False


@dataclass
class InList(ExprNode):
    expr: ExprNode
    items: list
    negated: bool = False


@dataclass
class SemiJoinCond(ExprNode):
    """Planner-internal conjunct produced by subquery decorrelation (never
    emitted by the parser): row passes iff a matching row exists (anti:
    does not exist) in `table` on probe_exprs[i] = build_cols[i]
    (ref: the semi-join LogicalJoin the reference's decorrelation rules
    produce, pkg/planner/core/rule_decorrelate.go)."""

    table: str  # materialized/real table name holding the subquery rows
    probe_exprs: list  # [ExprNode] over the outer schema
    build_cols: list  # [str] column names in `table`
    anti: bool = False
    require_notnull_probe: bool = False  # NOT IN: NULL probe would be wrong


@dataclass
class InSubquery(ExprNode):
    expr: ExprNode
    subquery: "SelectStmt"
    negated: bool = False


@dataclass
class Exists(ExprNode):
    subquery: "SelectStmt"
    negated: bool = False


@dataclass
class SubqueryExpr(ExprNode):
    """Scalar subquery."""

    subquery: "SelectStmt"


@dataclass
class CompareSubquery(ExprNode):
    """expr op ANY/ALL (subquery)."""

    expr: ExprNode
    op: str
    subquery: "SelectStmt"
    all: bool


@dataclass
class Like(ExprNode):
    expr: ExprNode
    pattern: ExprNode
    escape: str = "\\"
    negated: bool = False


@dataclass
class Regexp(ExprNode):
    expr: ExprNode
    pattern: ExprNode
    negated: bool = False


@dataclass
class Case(ExprNode):
    operand: Optional[ExprNode]
    when_clauses: list  # [(cond, result)]
    else_clause: Optional[ExprNode]


@dataclass
class Cast(ExprNode):
    expr: ExprNode
    to_type: "TypeSpec"


@dataclass
class Interval(ExprNode):
    value: ExprNode
    unit: str  # day/month/year/hour/minute/second/...


@dataclass
class Default(ExprNode):
    column: str = ""


@dataclass
class Variable(ExprNode):
    name: str
    system: bool  # @@x vs @x
    scope: str = ""  # "global" | "session" | ""


@dataclass
class RowExpr(ExprNode):
    items: list


# ---------------------------------------------------------------- type spec


@dataclass
class TypeSpec:
    """Column type in DDL / CAST (ref: pkg/parser/types FieldType AST form)."""

    name: str  # normalized lowercase: int/bigint/varchar/decimal/date/datetime/...
    length: int = -1
    decimal: int = -1
    unsigned: bool = False
    zerofill: bool = False
    charset: str = ""
    collate: str = ""
    elems: tuple = ()  # enum/set elements


# ---------------------------------------------------------------- table refs


@dataclass
class TableName:
    name: str
    db: str = ""
    alias: str = ""
    index_hints: list = field(default_factory=list)


@dataclass
class SubqueryTable:
    subquery: "SelectStmt"
    alias: str


@dataclass
class Join:
    left: object
    right: object
    kind: str  # "inner" | "left" | "right" | "cross"
    on: Optional[ExprNode] = None
    using: list = field(default_factory=list)


# ---------------------------------------------------------------- SELECT


@dataclass
class SelectField:
    expr: ExprNode
    alias: str = ""
    # verbatim source text of the expression — MySQL titles unaliased
    # expression columns with the text as written (ref: the reference's
    # field name derivation in planner buildProjectionField)
    source: str = ""


@dataclass
class ByItem:
    expr: ExprNode
    desc: bool = False


@dataclass
class Limit:
    count: Optional[ExprNode]
    offset: Optional[ExprNode] = None


@dataclass
class CTE:
    """One WITH-clause entry (ref: ast.CommonTableExpression)."""

    name: str
    columns: list  # [str] optional column aliases
    subquery: "SelectStmt"
    recursive: bool = False


@dataclass
class SelectStmt:  # noqa: PLR0902
    fields: list  # [SelectField|Star]
    from_clause: object = None  # TableName | SubqueryTable | Join | None
    where: Optional[ExprNode] = None
    group_by: list = field(default_factory=list)  # [ByItem]
    having: Optional[ExprNode] = None
    order_by: list = field(default_factory=list)  # [ByItem]
    limit: Optional[Limit] = None
    distinct: bool = False
    for_update: bool = False
    ctes: list = field(default_factory=list)  # [CTE]
    hints: list = field(default_factory=list)  # [(name, [args])] from /*+ */


@dataclass
class SetOprStmt:
    """UNION / EXCEPT / INTERSECT chains (ref: ast.SetOprStmt)."""

    selects: list  # [SelectStmt]
    all_flags: list  # [bool] between consecutive selects
    order_by: list = field(default_factory=list)
    limit: Optional[Limit] = None
    ops: list = field(default_factory=list)  # "union"|"except"|"intersect" per boundary
    ctes: list = field(default_factory=list)  # [CTE]


# ---------------------------------------------------------------- DML


@dataclass
class Assignment:
    column: ColumnName
    expr: ExprNode


@dataclass
class InsertStmt:
    table: TableName
    columns: list  # [str]
    values: list  # [[ExprNode]]
    select: Optional[SelectStmt] = None
    on_duplicate: list = field(default_factory=list)  # [Assignment]
    replace: bool = False
    ignore: bool = False


@dataclass
class UpdateStmt:
    table: object  # TableName | Join
    assignments: list  # [Assignment]
    where: Optional[ExprNode] = None
    order_by: list = field(default_factory=list)
    limit: Optional[Limit] = None


@dataclass
class DeleteStmt:
    table: TableName
    where: Optional[ExprNode] = None
    order_by: list = field(default_factory=list)
    limit: Optional[Limit] = None
    multi_table: bool = False  # DELETE t1,t2 FROM ... — parsed, rejected at exec


@dataclass
class LoadDataStmt:
    path: str
    table: TableName
    fields_terminated: str = "\t"
    fields_enclosed: str = ""
    lines_terminated: str = "\n"
    ignore_lines: int = 0
    columns: list = field(default_factory=list)


# ---------------------------------------------------------------- DDL


@dataclass
class ColumnDef:
    name: str
    type: TypeSpec
    not_null: bool = False
    default: Optional[ExprNode] = None
    auto_increment: bool = False
    primary_key: bool = False
    unique: bool = False
    comment: str = ""
    on_update_now: bool = False
    generated: Optional[ExprNode] = None  # GENERATED ALWAYS AS (expr)
    generated_stored: bool = False  # STORED vs VIRTUAL
    check: Optional[ExprNode] = None  # column CHECK constraint


@dataclass
class IndexDef:
    name: str
    columns: list  # [(col_name, prefix_len)]
    unique: bool = False
    primary: bool = False


@dataclass
class ForeignKeyDef:
    name: str
    columns: list
    ref_table: TableName
    ref_columns: list
    on_delete: str = "restrict"  # restrict | cascade | set_null | no_action
    on_update: str = "restrict"


@dataclass
class CreateTableStmt:
    table: TableName
    columns: list  # [ColumnDef]
    indexes: list = field(default_factory=list)  # [IndexDef]
    foreign_keys: list = field(default_factory=list)
    if_not_exists: bool = False
    options: dict = field(default_factory=dict)  # engine/charset/auto_increment/comment
    like: Optional[TableName] = None
    select: Optional[SelectStmt] = None


@dataclass
class DropTableStmt:
    tables: list  # [TableName]
    if_exists: bool = False


@dataclass
class TruncateTableStmt:
    table: TableName


@dataclass
class CreateDatabaseStmt:
    name: str
    if_not_exists: bool = False


@dataclass
class DropDatabaseStmt:
    name: str
    if_exists: bool = False


@dataclass
class CreateIndexStmt:
    index_name: str
    table: TableName
    columns: list  # [(col, prefix_len)]
    unique: bool = False


@dataclass
class DropIndexStmt:
    index_name: str
    table: TableName


@dataclass
class AlterTableSpec:
    """One ALTER TABLE action."""

    action: str  # add_column/drop_column/add_index/drop_index/modify_column/change_column/rename/add_primary/rename_index
    column: Optional[ColumnDef] = None
    index: Optional[IndexDef] = None
    name: str = ""  # old col/index name, or new table name for rename
    new_name: str = ""
    position: str = ""  # "" | "first" | "after:<col>"
    options: dict = field(default_factory=dict)  # table/partition options
    default: Optional[ExprNode] = None  # SET DEFAULT value


@dataclass
class AlterTableStmt:
    table: TableName
    specs: list  # [AlterTableSpec]


@dataclass
class RenameTableStmt:
    pairs: list  # [(TableName, TableName)]


# ---------------------------------------------------------------- misc stmts


@dataclass
class SetStmt:
    assignments: list  # [(scope, name, ExprNode)] scope in {"session","global","user"}


@dataclass
class UseStmt:
    db: str


@dataclass
class ShowStmt:
    kind: str  # databases/tables/columns/create_table/index/variables/status/warnings/processlist/engines/collation/charset/stats_meta
    table: Optional[TableName] = None
    db: str = ""
    pattern: Optional[str] = None
    where: Optional[ExprNode] = None
    full: bool = False
    global_scope: bool = False


@dataclass
class ExplainStmt:
    target: object  # statement
    analyze: bool = False
    format: str = "row"


@dataclass
class AnalyzeTableStmt:
    tables: list  # [TableName]
    columns: list = field(default_factory=list)


@dataclass
class CreateUserStmt:
    users: list  # [(name, host, password)]
    if_not_exists: bool = False


@dataclass
class DropUserStmt:
    users: list  # [(name, host)]
    if_exists: bool = False


@dataclass
class GrantStmt:
    privs: list  # ["select", ...] or ["all"]
    db: str  # "*" = all
    table: str  # "*" = all
    users: list  # [(name, host)]


@dataclass
class RevokeStmt:
    privs: list
    db: str
    table: str
    users: list


@dataclass
class BeginStmt:
    pass


@dataclass
class CommitStmt:
    pass


@dataclass
class RollbackStmt:
    pass


@dataclass
class PrepareStmt:
    name: str
    sql: str


@dataclass
class ExecuteStmt:
    name: str
    using: list = field(default_factory=list)  # [@var names]


@dataclass
class DeallocateStmt:
    name: str


@dataclass
class AdminStmt:
    kind: str  # check_table / show_ddl / show_ddl_jobs / cancel_ddl_jobs / checksum_table
    tables: list = field(default_factory=list)
    job_ids: list = field(default_factory=list)


@dataclass
class FlashbackStmt:
    table: TableName
    new_name: str = ""


@dataclass
class KillStmt:
    conn_id: int
    query_only: bool = False


@dataclass
class BRIEStmt:
    """BACKUP/RESTORE SQL (ref: br glue pkg/executor/brie.go). ISSUE 20
    adds the PITR forms: `BACKUP LOG TO ...` / `STOP BACKUP LOG TO ...`
    attach/detach a durable log backup (kind "backup_log" /
    "stop_backup_log"), and `RESTORE FROM ... UNTIL TS = n` replays the
    log to an exact ts (`until_ts` set)."""

    kind: str  # "backup" | "restore" | "backup_log" | "stop_backup_log"
    storage: str
    tables: list = field(default_factory=list)  # empty = full
    until_ts: int | None = None  # RESTORE ... UNTIL TS = n


@dataclass
class TraceStmt:
    target: object  # statement
    format: str = "row"  # 'row' | 'json' (ref: parser.y TraceStmt FORMAT)


@dataclass
class ChangefeedStmt:
    """CREATE/PAUSE/RESUME/DROP CHANGEFEED (ref: TiCDC's `cdc cli
    changefeed create --sink-uri=... --start-ts=...`, SQL-ified the way
    the reference SQL-ifies BR as BACKUP/RESTORE)."""

    action: str  # create | pause | resume | drop
    name: str
    sink_uri: str = ""
    tables: list = field(default_factory=list)  # [TableName]; empty = all
    options: dict = field(default_factory=dict)  # WITH k = v (start_ts, ...)


@dataclass
class CollateExpr(ExprNode):
    """expr COLLATE collation_name (ref: parser.y SimpleExpr collate)."""

    expr: ExprNode
    collation: str


@dataclass
class CreateViewStmt:
    """(ref: parser.y CreateViewStmt)."""

    name: "TableName"
    columns: list
    select: object
    or_replace: bool = False
    source: str = ""  # verbatim SELECT text (persisted as the view body)


@dataclass
class DropViewStmt:
    names: list
    if_exists: bool = False


@dataclass
class CreateSequenceStmt:
    name: "TableName"
    if_not_exists: bool = False
    options: dict = field(default_factory=dict)


@dataclass
class DropSequenceStmt:
    names: list
    if_exists: bool = False


@dataclass
class AlterUserStmt:
    """(ref: parser.y AlterUserStmt; options recorded, not all enforced)."""

    users: list
    if_exists: bool = False
    options: dict = field(default_factory=dict)


@dataclass
class ImportIntoStmt:
    """(ref: parser.y ImportIntoStmt — the disttask bulk-import entry)."""

    table: "TableName"
    columns: list
    path: str
    options: dict = field(default_factory=dict)


@dataclass
class BatchStmt:
    """BATCH [ON col] LIMIT n <dml> (ref: parser.y NonTransactionalDMLStmt)."""

    column: str
    limit: int
    inner: object


@dataclass
class SplitTableStmt:
    """SPLIT TABLE ... (ref: parser.y SplitRegionStmt)."""

    table: "TableName"
    index: str = ""
    between: tuple | None = None  # (lo exprs, hi exprs, regions)
    by_points: list = field(default_factory=list)  # [[exprs], ...]


@dataclass
class LoadStatsStmt:
    path: str


@dataclass
class BindingStmt:
    """CREATE/DROP [GLOBAL|SESSION] BINDING (ref: pkg/bindinfo)."""

    action: str  # create | drop
    scope: str  # global | session
    target: object  # bound statement AST
    hinted: object = None  # USING statement AST (create only)
    target_sql: str = ""  # display text (SHOW BINDINGS)
    hinted_sql: str = ""


@dataclass
class SavepointStmt:
    """SAVEPOINT / ROLLBACK TO [SAVEPOINT] / RELEASE SAVEPOINT."""

    action: str  # set | rollback | release
    name: str
