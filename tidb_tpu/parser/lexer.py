"""SQL lexer — MySQL dialect tokenizer.

The reference embeds a goyacc grammar with a hand-written lexer
(ref: pkg/parser/lexer.go, misc.go keyword table). Here the lexer is a
small hand-rolled scanner producing a flat token list the recursive-descent
parser consumes; same token classes: identifiers (bare + backquoted),
strings ('..', ".." with backslash escapes), numbers (int/float/hex),
operators, parameter markers, comments (--, #, /* */), case-insensitive
keywords.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class T(enum.Enum):
    IDENT = "ident"
    QIDENT = "qident"  # `quoted`
    STRING = "string"
    NUMBER = "number"
    HEX = "hex"
    PARAM = "param"  # ?
    OP = "op"
    HINT = "hint"  # /*+ ... */ optimizer hint body (ref: parser hintparser)
    EOF = "eof"


@dataclass(frozen=True)
class Token:
    kind: T
    text: str
    pos: int  # byte offset, for error messages

    @property
    def upper(self) -> str:
        return self.text.upper()


# Multi-char operators, longest first (ref: lexer.go startWithOp tables).
_OPS3 = ("<=>", "->>")
_OPS2 = ("<=", ">=", "<>", "!=", ":=", "||", "&&", "<<", ">>", "->")
_OPS1 = "+-*/%()=<>,.;@~&|^!"


class LexError(ValueError):
    pass


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            i += 1
            continue
        # comments
        if c == "#" or (c == "-" and sql[i : i + 3] in ("-- ", "--\t", "--\n") or sql[i : i + 2] == "--" and i + 2 == n):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql[i : i + 2] == "/*":
            j = sql.find("*/", i + 2)
            if j < 0:
                raise LexError(f"unterminated comment at {i}")
            # optimizer hint /*+ ... */ — one token carrying the body
            # (ref: pkg/parser hint comments -> hintparser)
            if sql[i + 2 : i + 3] == "+":
                # only a hint right after SELECT reaches the parser; in
                # every other position it degrades to a comment (matching
                # the pre-hint behavior for UPDATE/INSERT/DELETE, whose
                # grammars do not consume hint tokens yet)
                if toks and toks[-1].kind is T.IDENT and toks[-1].upper == "SELECT":
                    toks.append(Token(T.HINT, sql[i + 3 : j].strip(), i))
                i = j + 2
                continue
            # executable comment /*! ... */ — strip markers, lex body
            if sql[i + 2 : i + 3] == "!":
                body = sql[i + 3 : j]
                k = 0
                while k < len(body) and body[k].isdigit():
                    k += 1
                inner = tokenize(body[k:])
                toks.extend(t for t in inner if t.kind is not T.EOF)
            i = j + 2
            continue
        # strings
        if c in ("'", '"'):
            quote = c
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise LexError(f"unterminated string at {i}")
                ch = sql[j]
                if ch == "\\" and j + 1 < n:
                    esc = sql[j + 1]
                    buf.append({"n": "\n", "t": "\t", "r": "\r", "0": "\x00", "b": "\b", "Z": "\x1a"}.get(esc, esc))
                    j += 2
                    continue
                if ch == quote:
                    if sql[j + 1 : j + 2] == quote:  # doubled quote
                        buf.append(quote)
                        j += 2
                        continue
                    break
                buf.append(ch)
                j += 1
            toks.append(Token(T.STRING, "".join(buf), i))
            i = j + 1
            continue
        # backquoted identifier
        if c == "`":
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise LexError(f"unterminated identifier at {i}")
                if sql[j] == "`":
                    if sql[j + 1 : j + 2] == "`":
                        buf.append("`")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            toks.append(Token(T.QIDENT, "".join(buf), i))
            i = j + 1
            continue
        # numbers (and leading-dot floats)
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            if c == "0" and sql[i + 1 : i + 2] in ("x", "X"):
                j = i + 2
                while j < n and sql[j] in "0123456789abcdefABCDEF":
                    j += 1
                toks.append(Token(T.HEX, sql[i:j], i))
                i = j
                continue
            j = i
            seen_dot = seen_e = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_e:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_e and j + 1 < n and (sql[j + 1].isdigit() or sql[j + 1] in "+-"):
                    seen_e = True
                    j += 2 if sql[j + 1] in "+-" else 1
                else:
                    break
            toks.append(Token(T.NUMBER, sql[i:j], i))
            i = j
            continue
        # identifiers / keywords
        if c.isalpha() or c == "_" or c == "$":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] in "_$"):
                j += 1
            toks.append(Token(T.IDENT, sql[i:j], i))
            i = j
            continue
        if c == "?":
            toks.append(Token(T.PARAM, "?", i))
            i += 1
            continue
        op3 = sql[i : i + 3]
        if op3 in _OPS3:
            toks.append(Token(T.OP, op3, i))
            i += 3
            continue
        op2 = sql[i : i + 2]
        if op2 in _OPS2:
            toks.append(Token(T.OP, op2, i))
            i += 2
            continue
        if c in _OPS1:
            toks.append(Token(T.OP, c, i))
            i += 1
            continue
        raise LexError(f"unexpected character {c!r} at {i}")
    toks.append(Token(T.EOF, "", n))
    return toks
